"""Batched bin-packing kernel on TPU (JAX).

Reformulates the reference's sequential FFD loop
(ref: pkg/controllers/provisioning/binpacking/packer.go:82-189) as static-shape
tensor rounds:

  * pods are pre-collapsed into G groups of identical request vectors
    (ops.encode.group_pods); G is small (tens) even for 50k-pod batches.
  * one *round* fills a candidate node of every instance type at once —
    a lax.scan over groups, vmapped over the T types.
  * the chosen node fill is **replicated** k = min_{g: p_g>0} floor(c_g / p_g)
    times in one step. Replication is exact for greedy FFD: every one of those
    k nodes would have received an identical fill (the capacity ledger resets
    per node and group counts stay >= the fill). This collapses the reference's
    O(#nodes) sequential loop — 50k pods of one shape solve in one round.
  * rounds run under lax.while_loop with preallocated output buffers, so the
    whole solve is one XLA computation with static shapes (no recompiles
    across batches after bucketing).

Two selection modes:
  * mode="ffd": parity with the reference — the largest type sets the
    max-pods bound, the smallest type achieving it wins, and with quirk=True
    the fits()-early-exit quirk (packable.go:147-157, Cmp >= 0 rejecting exact
    fits) is reproduced bit-for-bit for cross-checking.
  * mode="cost": price-aware — each round picks the type minimizing
    $/(weighted work packed); used by the cost solver to beat greedy $/hr.

All shapes padded: G -> groups (counts 0), T -> types (valid_types mask).
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import NamedTuple, Tuple

import warnings

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-4
_INF = jnp.inf


def suppress_donation_advisory() -> None:
    """Silence jax's "Some donated buffers were not usable" UserWarning for
    this process. Buffer donation is a hint: backends that can't alias a
    donated input into an output (XLA:CPU for most shapes) ignore it and
    warn per compile, and on a CPU-fallback rig that advisory is expected
    noise, not a signal. Called by OUR process entry points (controller,
    sidecar, bench, smokes) — deliberately NOT at library import, so an
    application embedding this package keeps its own warning filters
    (pytest.ini applies the same filter for the test suite)."""
    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable"
    )


class PackRounds(NamedTuple):
    """Kernel output: up to MR rounds of (type, per-group fill, replication)."""

    round_type: jnp.ndarray  # [MR] int32 — chosen instance-type index
    round_fill: jnp.ndarray  # [MR, G] int32 — pods of each group per node
    round_repl: jnp.ndarray  # [MR] int32 — identical nodes this round
    num_rounds: jnp.ndarray  # [] int32
    unschedulable: jnp.ndarray  # [G] int32 — pods set aside per group
    overflow: jnp.ndarray  # [] bool — round budget exhausted (never expected)


def max_rounds(num_groups: int) -> int:
    # Every two rounds exhaust at least one group (replication drops the
    # binding group below its fill), so 2G+8 is a safe static budget.
    return 2 * num_groups + 8


def _fill_one_node(capacity, total, vectors, counts, *, quirk: bool):
    """Greedy-fill one node of one type. Returns packed count per group.

    Mirrors packable.go:113-132: groups scanned largest→smallest; a first
    active group that can't place one pod aborts the whole fill (the caller
    interprets an all-zero fill as "largest pod fits nowhere" for this type);
    with quirk=True, a failed placement stops the scan early once remaining
    capacity falls to/below the smallest active pod on any tracked dimension.
    """
    num_groups = vectors.shape[0]
    active = counts > 0
    any_active = jnp.any(active)
    first_active = jnp.argmax(active)
    last_active = num_groups - 1 - jnp.argmax(active[::-1])
    smallest = vectors[last_active]

    def step(carry, g):
        remaining, stopped, abort = carry
        vec = vectors[g]
        cnt = counts[g]
        ratio = jnp.where(vec > 0, remaining / jnp.where(vec > 0, vec, 1.0), _INF)
        n_fit = jnp.floor(jnp.min(ratio) + _EPS)
        n_fit = jnp.maximum(n_fit, 0.0).astype(jnp.int32)
        allowed = (cnt > 0) & ~stopped & ~abort
        n = jnp.where(allowed, jnp.minimum(cnt, n_fit), 0)
        abort = abort | ((g == first_active) & (cnt > 0) & (n == 0))
        remaining = remaining - n.astype(vectors.dtype) * vec
        failed = allowed & (n < cnt)
        if quirk:
            essentially_full = jnp.any((total > 0) & (remaining <= smallest + _EPS))
            stopped = stopped | (failed & essentially_full)
        return (remaining, stopped, abort), n

    (_, _, abort), packed = jax.lax.scan(
        step,
        (capacity, jnp.asarray(False), jnp.asarray(False)),
        jnp.arange(num_groups),
    )
    packed = jnp.where(abort | ~any_active, 0, packed)
    return packed


class _LoopState(NamedTuple):
    counts: jnp.ndarray
    round_type: jnp.ndarray
    round_fill: jnp.ndarray
    round_repl: jnp.ndarray
    num_rounds: jnp.ndarray
    unschedulable: jnp.ndarray
    iters: jnp.ndarray


@functools.partial(
    # NO donation here, deliberately: this kernel is traced INSIDE the
    # fused cost kernel, twice, over the same operands — inner-jit donation
    # would let XLA alias the first call's inputs into its outputs while
    # the second call (and the LP) still read them. Donation lives on the
    # TOP-LEVEL dispatch kernels only (models/solver._cost_fused_kernel,
    # ops/consolidate._counterfactual_kernel), where the buffers really are
    # dead after the call.
    jax.jit, static_argnames=("quirk", "mode")
)
def pack_kernel(
    vectors,  # [G, R] f32 — group request vectors, FFD-sorted desc
    counts,  # [G] i32 — pods per group
    capacity,  # [T, R] f32 — usable capacity per type (asc-sorted fleet)
    total,  # [T, R] f32 — raw capacity per type (for the quirk check)
    valid_types,  # [T] bool — padding mask
    prices,  # [T] f32 — $/hr per type (cost mode)
    *,
    quirk: bool = False,
    mode: str = "ffd",
) -> PackRounds:
    num_groups = vectors.shape[0]
    num_types = capacity.shape[0]
    mr = max_rounds(num_groups)

    # Weight per group for cost mode: the max utilization fraction across the
    # largest valid type's dimensions — "how much node does one pod consume".
    largest_valid = num_types - 1 - jnp.argmax(valid_types[::-1])
    ref_cap = jnp.maximum(capacity[largest_valid], 1.0)
    group_weight = jnp.max(vectors / ref_cap, axis=1)  # [G]

    def body(state: _LoopState) -> _LoopState:
        fills = jax.vmap(
            lambda cap, tot: _fill_one_node(
                cap, tot, vectors, state.counts, quirk=quirk
            )
        )(capacity, total)  # [T, G]
        fills = jnp.where(valid_types[:, None], fills, 0)
        sums = fills.sum(axis=1)  # [T]
        packs_any = (sums > 0) & valid_types

        if mode == "ffd":
            bound = sums[largest_valid]
            achieves = (sums == bound) & valid_types & (bound > 0)
            t_sel = jnp.argmax(achieves)  # first (smallest) achieving type
            have_pack = bound > 0
        elif mode == "cost":
            weighted = fills.astype(jnp.float32) @ group_weight  # [T]
            score = jnp.where(packs_any, prices / jnp.maximum(weighted, 1e-9), _INF)
            t_sel = jnp.argmin(score)
            have_pack = jnp.any(packs_any)
        else:
            raise ValueError(f"unknown mode {mode!r}")

        fill = fills[t_sel]  # [G]
        if quirk:
            # Replication must preserve each group's partial/full packing
            # status: once a partially-packed group's count drops to exactly
            # its fill, the "failed reserve" disappears and the fits()
            # early-exit no longer fires, changing later groups' packing
            # (observed in the reference when the last 1.5-pod pairs with a
            # 0.5-pod). So a partial group only replicates while count stays
            # strictly above fill: floor((c-1)/p); a fully-packed group
            # (p == c) exhausts and allows exactly 1.
            safe = jnp.where(
                fill == state.counts,
                1,
                jnp.maximum((state.counts - 1) // jnp.maximum(fill, 1), 1),
            )
        else:
            # Pure greedy: identical fills while counts stay >= fill.
            safe = state.counts // jnp.maximum(fill, 1)
        repl_per_group = jnp.where(fill > 0, safe, jnp.iinfo(jnp.int32).max)
        repl = jnp.maximum(jnp.min(repl_per_group), 1).astype(jnp.int32)

        # Pack branch.
        counts_packed = state.counts - repl * fill
        round_type = state.round_type.at[state.num_rounds].set(t_sel.astype(jnp.int32))
        round_fill = state.round_fill.at[state.num_rounds].set(fill.astype(jnp.int32))
        round_repl = state.round_repl.at[state.num_rounds].set(repl)

        # Unschedulable branch: retire the first group with pods remaining
        # (ref: packer.go:120-124 sets aside the largest pod; identical pods
        # fail identically, so the whole group retires at once).
        first_active = jnp.argmax(state.counts > 0)
        unsched = state.unschedulable.at[first_active].add(
            jnp.where(have_pack, 0, state.counts[first_active])
        )
        counts_unsched = state.counts.at[first_active].set(
            jnp.where(have_pack, state.counts[first_active], 0)
        )

        return _LoopState(
            counts=jnp.where(have_pack, counts_packed, counts_unsched),
            round_type=jnp.where(have_pack, round_type, state.round_type),
            round_fill=jnp.where(have_pack, round_fill, state.round_fill),
            round_repl=jnp.where(have_pack, round_repl, state.round_repl),
            num_rounds=state.num_rounds + jnp.where(have_pack, 1, 0),
            unschedulable=unsched,
            iters=state.iters + 1,
        )

    def cond(state: _LoopState):
        return (state.counts.sum() > 0) & (state.iters < mr + num_groups)

    init = _LoopState(
        counts=counts.astype(jnp.int32),
        round_type=jnp.zeros((mr,), jnp.int32),
        round_fill=jnp.zeros((mr, num_groups), jnp.int32),
        round_repl=jnp.zeros((mr,), jnp.int32),
        num_rounds=jnp.asarray(0, jnp.int32),
        unschedulable=jnp.zeros((num_groups,), jnp.int32),
        iters=jnp.asarray(0, jnp.int32),
    )
    final = jax.lax.while_loop(cond, body, init)
    # num_rounds can exceed the static mr budget (the 2G+8 bound is
    # heuristic): jax clamps the out-of-bounds scatter into the last slot,
    # silently corrupting it while num_rounds keeps counting. Surface that
    # as overflow — the candidate is unusable and scoring must skip it —
    # and clamp the reported count so hosts never read past the buffer.
    return PackRounds(
        round_type=final.round_type,
        round_fill=final.round_fill,
        round_repl=final.round_repl,
        num_rounds=jnp.minimum(final.num_rounds, mr),
        unschedulable=final.unschedulable,
        overflow=(final.counts.sum() > 0) | (final.num_rounds > mr),
    )


# --- constrained multi-level pack: the [L, G, T] dispatch --------------------
#
# The constraint compiler (karpenter_tpu/constraints/compiler.py) lowers pod
# affinity/anti-affinity, topology-spread, and the preference-relaxation
# ladder into per-level tensors; this kernel solves EVERY relaxation level in
# one vmapped dispatch and picks the strictest feasible level on device —
# replacing the host-side relax-retry loop (one solve per level per 1s
# requeue) with a single kernel call. Per level l:
#
#   * allow[l, g, t]   — group g may be packed onto type t at this level
#                        (ladder envelope ∩ spread-domain zone offering ∩
#                        affinity restrictions; fit is re-checked here).
#   * penalty[l, g, t] — additive $/pod-ish spread/affinity pressure, folded
#                        into the cost-mode score (ScheduleAnyway spread,
#                        preferred-term steering).
#   * counts[l, g]     — pods per group AT THIS LEVEL: domain-expanded
#                        sub-groups carry per-level water-filled takes, so a
#                        level that narrows the allowed domains redistributes
#                        its pods.
#   * conflict[g, h]   — g and h may not share a node (anti-affinity on the
#                        hostname key; sub-groups pinned to different
#                        domains).
#   * node_cap[g]      — max pods of g per node (hostname topology spread
#                        lowers to cap = max_skew; hostname self-anti-
#                        affinity to cap = 1).
#
# Level selection: the strictest (lowest-index) level minimizing total
# unschedulable pods wins; per-group the kernel also reports the first
# level at which that group alone was fully packable (the bookkeeping the
# selection TTL cache records instead of driving retries).

NODE_CAP_NONE = 2**30  # int32-safe "no per-node cap" sentinel


class LevelPack(NamedTuple):
    """Output of the [L, G, T] constrained dispatch: the chosen level's
    rounds plus the level-selection evidence."""

    rounds: PackRounds  # the chosen level's rounds (fields as PackRounds)
    chosen_level: jnp.ndarray  # [] int32 — strictest feasible level index
    group_level: jnp.ndarray  # [G] int32 — first feasible level per group (L if none)
    level_unsched: jnp.ndarray  # [L, G] int32 — unschedulable per level


def _fill_one_node_constrained(capacity, vectors, counts, allow, conflict, node_cap):
    """Greedy-fill one node of one type under constraint masks.

    Same largest-first scan as _fill_one_node (quirk-free), plus: groups with
    allow=False are skipped without aborting the fill; a group conflicting
    with one already placed on THIS node is skipped; per-group node caps
    bound the fill. The whole fill aborts only when the first *eligible*
    active group cannot place a single pod (FFD "largest fits nowhere")."""
    num_groups = vectors.shape[0]
    eligible = (counts > 0) & allow
    any_eligible = jnp.any(eligible)
    first_eligible = jnp.argmax(eligible)

    def step(carry, g):
        remaining, placed, abort = carry
        vec = vectors[g]
        cnt = counts[g]
        ratio = jnp.where(vec > 0, remaining / jnp.where(vec > 0, vec, 1.0), _INF)
        n_fit = jnp.floor(jnp.min(ratio) + _EPS)
        n_fit = jnp.maximum(n_fit, 0.0).astype(jnp.int32)
        conflicted = jnp.any(placed & conflict[g])
        allowed = eligible[g] & ~conflicted & ~abort
        n = jnp.where(
            allowed, jnp.minimum(jnp.minimum(cnt, n_fit), node_cap[g]), 0
        )
        abort = abort | ((g == first_eligible) & eligible[g] & ~conflicted & (n == 0))
        remaining = remaining - n.astype(vectors.dtype) * vec
        placed = placed | (jnp.arange(num_groups) == g) & (n > 0)
        return (remaining, placed, abort), n

    (_, _, abort), packed = jax.lax.scan(
        step,
        (capacity, jnp.zeros((num_groups,), bool), jnp.asarray(False)),
        jnp.arange(num_groups),
    )
    packed = jnp.where(abort | ~any_eligible, 0, packed)
    return packed


def _pack_one_level(
    vectors, counts, capacity, valid_types, prices, allow, penalty,
    conflict, node_cap, *, mode: str,
) -> PackRounds:
    """One relaxation level's full round loop — the constrained analogue of
    pack_kernel's body, vmapped over L by pack_kernel_levels."""
    num_groups = vectors.shape[0]
    num_types = capacity.shape[0]
    mr = max_rounds(num_groups)

    fits = jnp.all(vectors[:, None, :] <= capacity[None, :, :] + 1e-6, axis=-1)
    usable = allow & fits & valid_types[None, :]  # [G, T]
    packable = usable.any(axis=1)
    # Groups no type admits at this level retire immediately — without this
    # the round loop would spin on them until the iteration guard trips and
    # flags a phantom overflow.
    init_unsched = jnp.where(packable, 0, counts).astype(jnp.int32)
    counts0 = jnp.where(packable, counts, 0).astype(jnp.int32)

    largest_valid = num_types - 1 - jnp.argmax(valid_types[::-1])
    ref_cap = jnp.maximum(capacity[largest_valid], 1.0)
    group_weight = jnp.max(vectors / ref_cap, axis=1)  # [G]

    def body(state: _LoopState) -> _LoopState:
        fills = jax.vmap(
            lambda cap, allow_t: _fill_one_node_constrained(
                cap, vectors, state.counts, allow_t, conflict, node_cap
            )
        )(capacity, usable.T)  # [T, G]
        fills = jnp.where(valid_types[:, None], fills, 0)
        sums = fills.sum(axis=1)
        packs_any = (sums > 0) & valid_types

        if mode == "ffd":
            # Masked analogue of the reference bound: the best achievable
            # pod count this round; the smallest type achieving it wins.
            bound = jnp.max(sums)
            achieves = (sums == bound) & valid_types & (bound > 0)
            t_sel = jnp.argmax(achieves)
            have_pack = bound > 0
        elif mode == "cost":
            weighted = fills.astype(jnp.float32) @ group_weight  # [T]
            pen = jnp.sum(fills.astype(jnp.float32) * penalty.T, axis=1)  # [T]
            score = jnp.where(
                packs_any, (prices + pen) / jnp.maximum(weighted, 1e-9), _INF
            )
            t_sel = jnp.argmin(score)
            have_pack = jnp.any(packs_any)
        else:
            raise ValueError(f"unknown mode {mode!r}")

        fill = fills[t_sel]
        safe = state.counts // jnp.maximum(fill, 1)
        repl_per_group = jnp.where(fill > 0, safe, jnp.iinfo(jnp.int32).max)
        repl = jnp.maximum(jnp.min(repl_per_group), 1).astype(jnp.int32)

        counts_packed = state.counts - repl * fill
        round_type = state.round_type.at[state.num_rounds].set(t_sel.astype(jnp.int32))
        round_fill = state.round_fill.at[state.num_rounds].set(fill.astype(jnp.int32))
        round_repl = state.round_repl.at[state.num_rounds].set(repl)

        first_active = jnp.argmax(state.counts > 0)
        unsched = state.unschedulable.at[first_active].add(
            jnp.where(have_pack, 0, state.counts[first_active])
        )
        counts_unsched = state.counts.at[first_active].set(
            jnp.where(have_pack, state.counts[first_active], 0)
        )
        return _LoopState(
            counts=jnp.where(have_pack, counts_packed, counts_unsched),
            round_type=jnp.where(have_pack, round_type, state.round_type),
            round_fill=jnp.where(have_pack, round_fill, state.round_fill),
            round_repl=jnp.where(have_pack, round_repl, state.round_repl),
            num_rounds=state.num_rounds + jnp.where(have_pack, 1, 0),
            unschedulable=unsched,
            iters=state.iters + 1,
        )

    def cond(state: _LoopState):
        return (state.counts.sum() > 0) & (state.iters < mr + num_groups)

    init = _LoopState(
        counts=counts0,
        round_type=jnp.zeros((mr,), jnp.int32),
        round_fill=jnp.zeros((mr, num_groups), jnp.int32),
        round_repl=jnp.zeros((mr,), jnp.int32),
        num_rounds=jnp.asarray(0, jnp.int32),
        unschedulable=init_unsched,
        iters=jnp.asarray(0, jnp.int32),
    )
    final = jax.lax.while_loop(cond, body, init)
    return PackRounds(
        round_type=final.round_type,
        round_fill=final.round_fill,
        round_repl=final.round_repl,
        num_rounds=jnp.minimum(final.num_rounds, mr),
        unschedulable=final.unschedulable,
        overflow=(final.counts.sum() > 0) | (final.num_rounds > mr),
    )


@functools.partial(jax.jit, static_argnames=("mode", "constrain"))
def pack_kernel_levels(
    vectors,  # [G, R] f32 — sub-group request vectors, FFD-sorted desc
    level_counts,  # [L, G] i32 — per-level pods per sub-group
    capacity,  # [T, R] f32
    total,  # [T, R] f32 (layout parity with pack_kernel; the quirk-free
    #                     constrained fill does not read it)
    valid_types,  # [T] bool
    prices,  # [T] f32
    level_allow,  # [L, G, T] bool
    level_penalty,  # [L, G, T] f32
    conflict,  # [G, G] bool
    node_cap,  # [G] i32 (NODE_CAP_NONE = uncapped)
    *,
    mode: str = "cost",
    constrain=None,
) -> LevelPack:
    """THE [L, G, T] dispatch: solve every relaxation level, pick the
    strictest feasible one on device. `constrain` is the mesh hook
    (parallel/sharded_solver.constrained_level_sharding): it shards the L
    axis over the device mesh so each chip solves its own levels — the round
    loops are sequential state machines, but levels are embarrassingly
    parallel — with one tiny cross-L argmin collective at the tail."""
    del total
    num_levels = level_counts.shape[0]
    lg = (lambda x: x) if constrain is None else constrain
    level_counts = lg(level_counts)
    level_allow = lg(level_allow)
    level_penalty = lg(level_penalty)

    per_level = jax.vmap(
        functools.partial(
            _pack_one_level,
            vectors,
            capacity=capacity,
            valid_types=valid_types,
            prices=prices,
            conflict=conflict,
            node_cap=node_cap,
            mode=mode,
        )
    )(level_counts, allow=level_allow, penalty=level_penalty)

    unsched = per_level.unschedulable  # [L, G]
    overflow = per_level.overflow  # [L] bool
    # A level's miss count is its unschedulable pods PLUS its assignment
    # shortfall: a level whose domain restrictions dropped pods from the
    # counts entirely (the compiler zeroes sub-groups whose domain the
    # level forbids) must not look feasible just because nothing it was
    # given went unplaced. The fullest level defines the batch demand.
    assigned = level_counts.sum(axis=1)  # [L]
    shortfall = jnp.max(assigned) - assigned
    totals = (
        unsched.sum(axis=1) + shortfall + overflow.astype(jnp.int32) * (2**30)
    )
    chosen = jnp.argmin(totals).astype(jnp.int32)  # first min = strictest
    rounds = jax.tree_util.tree_map(lambda leaf: leaf[chosen], per_level)
    feasible = (unsched == 0) & ~overflow[:, None]  # [L, G]
    group_level = jnp.where(
        feasible.any(axis=0), jnp.argmax(feasible, axis=0), num_levels
    ).astype(jnp.int32)
    return LevelPack(
        rounds=rounds,
        chosen_level=chosen,
        group_level=group_level,
        level_unsched=unsched,
    )


def pad_to(array: np.ndarray, size: int, axis: int = 0, value=0) -> np.ndarray:
    pad = size - array.shape[axis]
    if pad <= 0:
        return array
    widths = [(0, 0)] * array.ndim
    widths[axis] = (0, pad)
    return np.pad(array, widths, constant_values=value)


def bucket_size(n: int, minimum: int = 8) -> int:
    """Next power of two >= n — shape bucketing to avoid recompile storms
    (SURVEY.md §7 hard parts: dynamic shapes)."""
    size = minimum
    while size < n:
        size *= 2
    return size


# --- on-device plan compaction ----------------------------------------------
#
# The dense PackRounds state is mostly padding: round_fill is [MR, G] but a
# real plan touches a handful of (round, group) cells, and on a tunneled
# accelerator every byte fetched rides the same ~70ms round trip. The
# compaction post-pass runs ON DEVICE at the tail of the fused kernel and
# squeezes each candidate plan into per-round (type, repl) rows plus a
# prefix-sum-compacted COO list of the nonzero fill entries — a few KB for
# the headline 50k-pod solve instead of the 38KB padded state. Decode
# (decompact_plan) rebuilds the exact dense arrays, so everything downstream
# of the fetch is bit-identical to the dense path.


def entry_budget(num_groups: int) -> int:
    """Static COO entry budget per candidate plan: 4 entries per round.
    Opening FFD rounds touch many groups but replication retires them fast,
    so real plans sit far below this; a plan that overflows the budget sets
    the payload's nnz past it and the caller falls back to fetching the
    dense spill (correctness never depends on the budget)."""
    return 4 * max_rounds(num_groups)


def compact_words(num_groups: int) -> int:
    """int32 word count of compact_plan's payload for a padded group axis —
    THE shape math `make fetch-smoke` holds the fetch budget against."""
    mr = max_rounds(num_groups)
    budget = entry_budget(num_groups)
    per_candidate = mr + mr + 1 + num_groups + 1 + 1 + 2 * budget
    return 2 * per_candidate + num_groups


def compact_bytes(num_groups: int) -> int:
    """Total eager fetch payload in bytes: the compact int32 words plus the
    one float32 LP objective."""
    return 4 * compact_words(num_groups) + 4


def fetch_bytes(tree) -> int:
    """Total bytes of an output pytree — the per-solve device->host payload
    (published by bench.py per fetch path). THE byte accounting, shared by
    the solver handles and the consolidation eager fetch so the two can't
    drift from the real layouts."""
    return sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
    )


def _compact_rounds(rounds: PackRounds):
    """Device-side compaction of one PackRounds: fixed-size int32 segments
    [round_type, round_repl, num_rounds, unschedulable, overflow, nnz,
    entry_idx, entry_fill]. entry_idx holds flat r*G+g indices of nonzero
    round_fill cells, front-compacted by prefix sum; indices past the entry
    budget are dropped by the scatter (mode="drop") and signalled via nnz."""
    num_groups = rounds.round_fill.shape[1]
    budget = entry_budget(num_groups)
    flat = rounds.round_fill.reshape(-1)
    mask = flat != 0
    nnz = mask.sum().astype(jnp.int32)
    position = jnp.cumsum(mask) - 1
    dest = jnp.where(mask, position, budget)
    entry_idx = (
        jnp.zeros((budget,), jnp.int32)
        .at[dest]
        .set(jnp.arange(flat.shape[0], dtype=jnp.int32), mode="drop")
    )
    entry_fill = (
        jnp.zeros((budget,), jnp.int32)
        .at[dest]
        .set(flat.astype(jnp.int32), mode="drop")
    )
    return [
        rounds.round_type.astype(jnp.int32),
        rounds.round_repl.astype(jnp.int32),
        rounds.num_rounds.reshape(1).astype(jnp.int32),
        rounds.unschedulable.astype(jnp.int32),
        rounds.overflow.astype(jnp.int32).reshape(1),
        nnz.reshape(1),
        entry_idx,
        entry_fill,
    ]


def compact_plan(rounds_ffd: PackRounds, rounds_cost: PackRounds, feasible_any):
    """Both candidate plans plus the feasibility vector as ONE flat int32
    array — the eager device->host payload of a fused cost solve."""
    return jnp.concatenate(
        _compact_rounds(rounds_ffd)
        + _compact_rounds(rounds_cost)
        + [feasible_any.astype(jnp.int32)]
    )


def decompact_plan(
    words: np.ndarray, num_groups: int
) -> Tuple[PackRounds, PackRounds, np.ndarray, bool]:
    """Host-side inverse of compact_plan: (rounds_ffd, rounds_cost,
    feasible_any, ok) with the dense [MR, G] fill matrices rebuilt
    bit-identically. ok=False when either plan overflowed the COO entry
    budget — the caller must fetch the dense spill instead."""
    mr = max_rounds(num_groups)
    budget = entry_budget(num_groups)
    cursor = 0

    def take(n):
        nonlocal cursor
        out = words[cursor : cursor + n]
        cursor += n
        return out

    plans = []
    ok = True
    for _ in range(2):
        round_type = take(mr)
        round_repl = take(mr)
        num_rounds = take(1)[0]
        unschedulable = take(num_groups)
        overflow = bool(take(1)[0])
        nnz = int(take(1)[0])
        entry_idx = take(budget)
        entry_fill = take(budget)
        if nnz > budget:
            ok = False
            plans.append(None)
            continue
        fill = np.zeros((mr * num_groups,), np.int32)
        fill[entry_idx[:nnz]] = entry_fill[:nnz]
        plans.append(
            PackRounds(
                round_type=round_type,
                round_fill=fill.reshape(mr, num_groups),
                round_repl=round_repl,
                num_rounds=num_rounds,
                unschedulable=unschedulable,
                overflow=overflow,
            )
        )
    feasible_any = take(num_groups).astype(bool)
    return plans[0], plans[1], feasible_any, ok


# --- shard-local plan compaction ---------------------------------------------
#
# On a multi-chip mesh the dense [MR, G] round state used to be force-
# replicated before compaction (PR 6 pinned it: letting GSPMD partition the
# prefix-sum + scatter produced shard-strided indices and a shard-multiplied
# nnz). Shard-local compaction takes manual control instead: shard_map splits
# the G axis into one contiguous block per device, each device runs the SAME
# prefix-sum compaction over its own block with a block-local entry budget,
# and the only collective at the compaction step is the all-gather of the
# already-compacted segments — a few KB ride the ICI instead of the whole
# [MR, G] tensor. Decode (decompact_plan_sharded) scatters each shard's
# entries at its block offset, so the rebuilt dense arrays are bit-identical
# to the dense path, exactly like the single-device layout.


def shard_entry_budget(num_groups: int, shards: int) -> int:
    """Per-shard COO entry budget: the single-device budget formula applied
    to the shard's own group block, so the entries-per-group headroom (~8)
    is the same at every shard count. A shard whose block draws more than
    its budget signals overflow via nnz and the caller falls back to the
    dense spill — correctness never depends on the budget."""
    return entry_budget(num_groups // shards)


def compact_words_sharded(num_groups: int, shards: int) -> int:
    """int32 word count of compact_plan_sharded's payload (shards=1 is
    exactly the single-device compact_words layout)."""
    if shards <= 1:
        return compact_words(num_groups)
    mr = max_rounds(num_groups)
    budget = shard_entry_budget(num_groups, shards)
    per_candidate = mr + mr + 1 + num_groups + 1 + shards * (1 + 2 * budget)
    return 2 * per_candidate + num_groups


def _compact_entry_block(fill_block: jnp.ndarray, budget: int):
    """The prefix-sum COO compaction of one [MR, G_block] fill matrix into
    [nnz, entry_idx[budget], entry_fill[budget]] — the shared core of the
    single-device and shard-local layouts (indices are block-local)."""
    flat = fill_block.reshape(-1)
    mask = flat != 0
    nnz = mask.sum().astype(jnp.int32)
    position = jnp.cumsum(mask) - 1
    dest = jnp.where(mask, position, budget)
    entry_idx = (
        jnp.zeros((budget,), jnp.int32)
        .at[dest]
        .set(jnp.arange(flat.shape[0], dtype=jnp.int32), mode="drop")
    )
    entry_fill = (
        jnp.zeros((budget,), jnp.int32)
        .at[dest]
        .set(flat.astype(jnp.int32), mode="drop")
    )
    return jnp.concatenate([nnz.reshape(1), entry_idx, entry_fill])


def _compact_rounds_sharded(rounds: PackRounds, mesh):
    """Shard-local compaction of one PackRounds over `mesh`: the replicated
    header segments (round_type/repl/num_rounds/unschedulable/overflow) plus
    one [nnz, idx, fill] segment per device, produced by shard_map over the
    G axis split across BOTH mesh axes (block order = mesh device order)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    num_groups = rounds.round_fill.shape[1]
    shards = mesh.devices.size
    budget = shard_entry_budget(num_groups, shards)
    axes = tuple(mesh.axis_names)

    segments = shard_map(
        functools.partial(_compact_entry_block, budget=budget),
        mesh=mesh,
        in_specs=P(None, axes),
        out_specs=P(axes),
        # The block computation is deterministic from its slice; replication
        # checking can't see that through the scatter, so it is disabled.
        check_rep=False,
    )(rounds.round_fill)
    return [
        rounds.round_type.astype(jnp.int32),
        rounds.round_repl.astype(jnp.int32),
        rounds.num_rounds.reshape(1).astype(jnp.int32),
        rounds.unschedulable.astype(jnp.int32),
        rounds.overflow.astype(jnp.int32).reshape(1),
        segments,
    ]


def compact_plan_sharded(
    rounds_ffd: PackRounds, rounds_cost: PackRounds, feasible_any, *, mesh
):
    """compact_plan's multi-chip twin: both candidate plans plus the
    feasibility vector as one flat int32 array, with the COO entry lists
    compacted shard-locally (see the section comment). The G axis must be
    padded to a multiple of mesh.devices.size (models/solver.pad_kernel_args
    handles it via g_mult)."""
    if mesh.devices.size <= 1:
        return compact_plan(rounds_ffd, rounds_cost, feasible_any)
    return jnp.concatenate(
        _compact_rounds_sharded(rounds_ffd, mesh)
        + _compact_rounds_sharded(rounds_cost, mesh)
        + [feasible_any.astype(jnp.int32)]
    )


def decompact_plan_sharded(
    words: np.ndarray, num_groups: int, shards: int
) -> Tuple[PackRounds, PackRounds, np.ndarray, bool]:
    """Host-side inverse of compact_plan_sharded: scatter each shard's
    block-local entries at its block offset. shards=1 delegates to the
    single-device decoder (identical layout). ok=False when any shard of
    either plan overflowed its entry budget — the caller must fetch the
    dense spill instead."""
    if shards <= 1:
        return decompact_plan(words, num_groups)
    mr = max_rounds(num_groups)
    budget = shard_entry_budget(num_groups, shards)
    group_block = num_groups // shards
    cursor = 0

    def take(n):
        nonlocal cursor
        out = words[cursor : cursor + n]
        cursor += n
        return out

    plans = []
    ok = True
    for _ in range(2):
        round_type = take(mr)
        round_repl = take(mr)
        num_rounds = take(1)[0]
        unschedulable = take(num_groups)
        overflow = bool(take(1)[0])
        fill = np.zeros((mr * num_groups,), np.int32)
        plan_ok = True
        for shard in range(shards):
            nnz = int(take(1)[0])
            entry_idx = take(budget)
            entry_fill = take(budget)
            if nnz > budget:
                plan_ok = False
                continue
            rows = entry_idx[:nnz] // group_block
            cols = shard * group_block + entry_idx[:nnz] % group_block
            fill[rows * num_groups + cols] = entry_fill[:nnz]
        if not plan_ok:
            ok = False
            plans.append(None)
            continue
        plans.append(
            PackRounds(
                round_type=round_type,
                round_fill=fill.reshape(mr, num_groups),
                round_repl=round_repl,
                num_rounds=num_rounds,
                unschedulable=unschedulable,
                overflow=overflow,
            )
        )
    feasible_any = take(num_groups).astype(bool)
    return plans[0], plans[1], feasible_any, ok


# --- device-resident encode reuse --------------------------------------------

# Content-keyed cache of device handles for padded encode arrays (fleet
# capacity/total/valid/prices, consolidation type arrays): back-to-back
# sweeps in one reconcile turn (provision -> consolidate) re-derive the same
# encoded state, and without the cache every dispatch pays a fresh
# host->device transfer for it. Keyed by content, not object identity, so a
# rebuilt-but-identical fleet still hits. NEVER pass a cached handle as a
# donated argument — donation kills the buffer after one call.
_DEVICE_RESIDENT: "OrderedDict[Tuple, object]" = OrderedDict()
_DEVICE_RESIDENT_MAX = 64
_device_resident_lock = threading.Lock()


def device_resident(array: np.ndarray):
    """A device handle holding `array`'s contents, shared across dispatches
    with equal content. Pass-through for anything already on device."""
    if not isinstance(array, np.ndarray):
        return array
    key = (array.shape, array.dtype.str, array.tobytes())
    with _device_resident_lock:
        cached = _DEVICE_RESIDENT.get(key)
        if cached is not None:
            _DEVICE_RESIDENT.move_to_end(key)
            return cached
    # The transfer runs OUTSIDE the lock (device work must not serialize
    # unrelated dispatch threads); a racing double-put is harmless — last
    # writer wins and the loser's handle is dropped.
    handle = jax.device_put(array)
    with _device_resident_lock:
        while len(_DEVICE_RESIDENT) >= _DEVICE_RESIDENT_MAX:
            _DEVICE_RESIDENT.popitem(last=False)
        _DEVICE_RESIDENT[key] = handle
    return handle


def reset_device_resident() -> None:
    """Test hook: drop every cached device handle."""
    with _device_resident_lock:
        _DEVICE_RESIDENT.clear()
