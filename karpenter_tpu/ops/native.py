"""ctypes binding for the native host kernels (native/ffd.cc).

The reference's hot loop is compiled Go (binpacking/packer.go); ours is
C++ behind this binding, playing the same role: the fast host-side packer
used when no accelerator is attached, and the honest "compiled host
baseline" in benchmarks (a Python baseline would flatter the TPU numbers).

The shared library is built on demand with `make -C native` (g++ -O3). If no
toolchain is available the binding reports unavailable and callers fall back
to the pure-Python FFD — the framework never hard-requires native code.
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_NATIVE_DIR = _REPO_ROOT / "native"
_LIB_PATH = _NATIVE_DIR / "build" / "libktpu_ffd.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    try:
        result = subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            capture_output=True,
            timeout=120,
        )
        return result.returncode == 0 and _LIB_PATH.exists()
    except (OSError, subprocess.TimeoutExpired):
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        # Always run make: a no-op when fresh, a rebuild when ffd.cc changed
        # (loading a stale binary would silently bypass source edits), and a
        # from-scratch build when the artifact is absent (it is untracked —
        # -march=native output is not portable across machines).
        if not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
        except OSError:
            _load_failed = True
            return None
        lib.ktpu_ffd_pack.restype = ctypes.c_int
        lib.ktpu_ffd_pack.argtypes = [
            ctypes.POINTER(ctypes.c_float),  # vectors
            ctypes.POINTER(ctypes.c_int64),  # counts
            ctypes.c_int,  # num_groups
            ctypes.c_int,  # dims
            ctypes.POINTER(ctypes.c_float),  # capacity
            ctypes.POINTER(ctypes.c_float),  # total
            ctypes.c_int,  # num_types
            ctypes.c_int,  # quirk
            ctypes.POINTER(ctypes.c_int),  # round_type
            ctypes.POINTER(ctypes.c_int64),  # round_fill
            ctypes.POINTER(ctypes.c_int64),  # round_repl
            ctypes.POINTER(ctypes.c_int64),  # unschedulable
            ctypes.c_int,  # max_rounds
        ]
        lib.ktpu_lp_realize.restype = ctypes.c_int
        lib.ktpu_lp_realize.argtypes = [
            ctypes.POINTER(ctypes.c_float),  # vectors
            ctypes.c_int,  # num_groups
            ctypes.c_int,  # dims
            ctypes.POINTER(ctypes.c_int64),  # assignment [T x G]
            ctypes.POINTER(ctypes.c_float),  # capacity
            ctypes.POINTER(ctypes.c_float),  # total
            ctypes.c_int,  # num_types
            ctypes.POINTER(ctypes.c_int),  # round_type
            ctypes.POINTER(ctypes.c_int64),  # round_fill
            ctypes.POINTER(ctypes.c_int64),  # round_repl
            ctypes.c_int,  # max_rounds
        ]
        lib.ktpu_mix_enumerate.restype = ctypes.c_int
        lib.ktpu_mix_enumerate.argtypes = [
            ctypes.POINTER(ctypes.c_float),  # vectors
            ctypes.POINTER(ctypes.c_int64),  # counts
            ctypes.c_int,  # num_groups
            ctypes.c_int,  # dims
            ctypes.POINTER(ctypes.c_float),  # capacity (pre-gathered cands)
            ctypes.c_int,  # num_cand
            ctypes.POINTER(ctypes.c_int),  # seed_groups
            ctypes.c_int,  # num_seeds
            ctypes.POINTER(ctypes.c_float),  # fracs
            ctypes.c_int,  # num_fracs
            ctypes.POINTER(ctypes.c_uint64),  # hash mixers
            ctypes.POINTER(ctypes.c_int64),  # out fills
            ctypes.POINTER(ctypes.c_int),  # out type (candidate index)
            ctypes.c_int,  # max_out
        ]
        lib.ktpu_pool_select.restype = None
        lib.ktpu_pool_select.argtypes = [
            ctypes.POINTER(ctypes.c_double),  # demand [F x D]
            ctypes.c_int,  # num_fills
            ctypes.c_int,  # dims
            ctypes.POINTER(ctypes.c_float),  # capacity
            ctypes.POINTER(ctypes.c_int),  # row_types
            ctypes.POINTER(ctypes.c_double),  # row_prices
            ctypes.c_int,  # num_rows
            ctypes.c_int,  # max_rows
            ctypes.c_int,  # min_rows
            ctypes.c_double,  # band
            ctypes.c_double,  # ceiling_ratio
            ctypes.c_int,  # max_types
            ctypes.POINTER(ctypes.c_int),  # out_rows [F x max_rows]
            ctypes.POINTER(ctypes.c_int),  # out_counts [F]
        ]
        lib.ktpu_mix_price.restype = None
        lib.ktpu_mix_price.argtypes = [
            ctypes.POINTER(ctypes.c_double),  # demand [J x D]
            ctypes.c_int,  # num_cols
            ctypes.c_int,  # dims
            ctypes.POINTER(ctypes.c_float),  # capacity
            ctypes.POINTER(ctypes.c_double),  # pool_floor
            ctypes.POINTER(ctypes.c_int),  # order (price-ascending)
            ctypes.c_int,  # num_types
            ctypes.POINTER(ctypes.c_double),  # out prices
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def ffd_pack_rounds(
    vectors: np.ndarray,
    counts: np.ndarray,
    capacity: np.ndarray,
    total: np.ndarray,
    quirk: bool = True,
) -> Optional[Tuple[List[Tuple[int, np.ndarray, int]], np.ndarray]]:
    """Run the native FFD. Returns (rounds, unschedulable_counts) with rounds
    as (type index, fill per group, replication) — the same decode format the
    TPU kernel emits — or None when the native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    vectors = np.ascontiguousarray(vectors, dtype=np.float32)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    capacity = np.ascontiguousarray(capacity, dtype=np.float32)
    total = np.ascontiguousarray(total, dtype=np.float32)
    num_groups, dims = vectors.shape
    num_types = capacity.shape[0]
    max_rounds = int(counts.sum()) + 1
    round_type = np.zeros(max_rounds, dtype=np.int32)
    round_fill = np.zeros((max_rounds, max(num_groups, 1)), dtype=np.int64)
    round_repl = np.zeros(max_rounds, dtype=np.int64)
    unschedulable = np.zeros(max(num_groups, 1), dtype=np.int64)

    def ptr(array, ctype):
        return array.ctypes.data_as(ctypes.POINTER(ctype))

    rounds = lib.ktpu_ffd_pack(
        ptr(vectors, ctypes.c_float),
        ptr(counts, ctypes.c_int64),
        num_groups,
        dims,
        ptr(capacity, ctypes.c_float),
        ptr(total, ctypes.c_float),
        num_types,
        1 if quirk else 0,
        ptr(round_type, ctypes.c_int),
        ptr(round_fill, ctypes.c_int64),
        ptr(round_repl, ctypes.c_int64),
        ptr(unschedulable, ctypes.c_int64),
        max_rounds,
    )
    if rounds < 0:
        return None
    round_list = [
        (int(round_type[r]), round_fill[r, :num_groups], int(round_repl[r]))
        for r in range(rounds)
    ]
    return round_list, unschedulable[:num_groups]


# lp_realize sentinel: the native code determined the assignment cannot be
# realized (an assigned pod fits nowhere on its type) — distinct from None
# (library unavailable / buffer overflow), where a pure-Python retry is
# worthwhile.
INFEASIBLE = "infeasible"

# Don't pre-allocate more than this for the round buffers; past it the
# pure-Python realization (which allocates per round) is the safer path.
# The buffers are np.empty (never zero-filled — the C++ writes every cell of
# each round it returns), so below the cap the cost is address space, not
# touched pages, and the cap only needs to guard true pathologies.
_MAX_REALIZE_BUFFER_BYTES = 512 << 20


def lp_realize(
    vectors: np.ndarray,
    assignment: np.ndarray,
    capacity: np.ndarray,
    total: np.ndarray,
):
    """Realize an integerized [G, T] LP assignment as replication-compressed
    per-type greedy node fills (native). Returns the round list; INFEASIBLE
    when the native code proves the assignment unrealizable (callers drop the
    candidate); None when the library is unavailable or the problem exceeds
    the buffer envelope (callers fall back to pure Python)."""
    lib = load()
    if lib is None:
        return None
    vectors = np.ascontiguousarray(vectors, dtype=np.float32)
    capacity = np.ascontiguousarray(capacity, dtype=np.float32)
    total = np.ascontiguousarray(total, dtype=np.float32)
    num_groups, dims = vectors.shape
    num_types = capacity.shape[0]
    # [T x G] row-major for per-type column scans.
    assignment_tg = np.ascontiguousarray(assignment.T, dtype=np.int64)
    # Rounds scale with the assignment's nonzero entries, not T*G: each
    # round's binding group drops below its fill, so a (type, group) entry
    # contributes O(1) rounds. 4x + slack headroom; overflow (-1) falls back
    # to the unbounded pure-Python path.
    nnz = int(np.count_nonzero(assignment_tg))
    active = int((assignment_tg.sum(axis=1) > 0).sum())
    max_rounds = 4 * nnz + 16 * active + 64
    if max_rounds * max(num_groups, 1) * 8 > _MAX_REALIZE_BUFFER_BYTES:
        return None
    round_type = np.empty(max_rounds, dtype=np.int32)
    round_fill = np.empty((max_rounds, max(num_groups, 1)), dtype=np.int64)
    round_repl = np.empty(max_rounds, dtype=np.int64)

    def ptr(array, ctype):
        return array.ctypes.data_as(ctypes.POINTER(ctype))

    rounds = lib.ktpu_lp_realize(
        ptr(vectors, ctypes.c_float),
        num_groups,
        dims,
        ptr(assignment_tg, ctypes.c_int64),
        ptr(capacity, ctypes.c_float),
        ptr(total, ctypes.c_float),
        num_types,
        ptr(round_type, ctypes.c_int),
        ptr(round_fill, ctypes.c_int64),
        ptr(round_repl, ctypes.c_int64),
        max_rounds,
    )
    if rounds == -2:
        return INFEASIBLE
    if rounds < 0:
        return None
    # Copy row slices so the (possibly large) backing buffer isn't pinned by
    # views held through decode.
    return [
        (int(round_type[r]), round_fill[r, :num_groups].copy(), int(round_repl[r]))
        for r in range(rounds)
    ]


def mix_enumerate(
    vectors: np.ndarray,
    counts: np.ndarray,
    cand_capacity: np.ndarray,  # [C, D] pre-gathered candidate-type capacity
    seed_groups: np.ndarray,
    fracs: np.ndarray,
    mixers: np.ndarray,  # [G] uint64 hash multipliers (dedup key)
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native pair-seeded fill enumeration for the column-LP mix candidate
    (ops/mix_pack.py). Returns (fills [J, G] int64, candidate index [J]
    int32) deduped, or None when the library is unavailable / overflow."""
    lib = load()
    if lib is None:
        return None
    vectors = np.ascontiguousarray(vectors, dtype=np.float32)
    counts = np.ascontiguousarray(counts, dtype=np.int64)
    cand_capacity = np.ascontiguousarray(cand_capacity, dtype=np.float32)
    seed_groups = np.ascontiguousarray(seed_groups, dtype=np.int32)
    fracs = np.ascontiguousarray(fracs, dtype=np.float32)
    mixers = np.ascontiguousarray(mixers, dtype=np.uint64)
    num_groups, dims = vectors.shape
    num_cand = cand_capacity.shape[0]
    max_out = num_cand * len(seed_groups) * len(fracs) * len(seed_groups) + 1
    out_fills = np.empty((max_out, max(num_groups, 1)), dtype=np.int64)
    out_type = np.empty(max_out, dtype=np.int32)

    def ptr(array, ctype):
        return array.ctypes.data_as(ctypes.POINTER(ctype))

    written = lib.ktpu_mix_enumerate(
        ptr(vectors, ctypes.c_float),
        ptr(counts, ctypes.c_int64),
        num_groups,
        dims,
        ptr(cand_capacity, ctypes.c_float),
        num_cand,
        ptr(seed_groups, ctypes.c_int),
        len(seed_groups),
        ptr(fracs, ctypes.c_float),
        len(fracs),
        ptr(mixers, ctypes.c_uint64),
        ptr(out_fills, ctypes.c_int64),
        ptr(out_type, ctypes.c_int),
        max_out,
    )
    if written < 0:
        return None
    return out_fills[:written].copy(), out_type[:written].copy()


def mix_price(
    demand: np.ndarray,  # [J, D] float64 column demand
    capacity: np.ndarray,  # [T, D]
    pool_floor: np.ndarray,  # [T] float64
    order: np.ndarray,  # [T] int32 type indices, price-ascending
) -> Optional[np.ndarray]:
    """Native demand-dominance pricing (first feasible type in price order).
    Returns [J] float64 prices or None when the library is unavailable."""
    lib = load()
    if lib is None:
        return None
    demand = np.ascontiguousarray(demand, dtype=np.float64)
    capacity = np.ascontiguousarray(capacity, dtype=np.float32)
    pool_floor = np.ascontiguousarray(pool_floor, dtype=np.float64)
    order = np.ascontiguousarray(order, dtype=np.int32)
    num_cols, dims = demand.shape
    out = np.empty(num_cols, dtype=np.float64)

    def ptr(array, ctype):
        return array.ctypes.data_as(ctypes.POINTER(ctype))

    lib.ktpu_mix_price(
        ptr(demand, ctypes.c_double),
        num_cols,
        dims,
        ptr(capacity, ctypes.c_float),
        ptr(pool_floor, ctypes.c_double),
        ptr(order, ctypes.c_int),
        capacity.shape[0],
        ptr(out, ctypes.c_double),
    )
    return out


def pool_select_batch(
    demand: np.ndarray,  # [F, D] float64 per-fill demand
    capacity: np.ndarray,  # [T, D]
    row_types: np.ndarray,  # [N] int32 global price-sorted pool order
    row_prices: np.ndarray,  # [N] float64
    max_rows: int,
    min_rows: int,
    band: float,
    ceiling_ratio: float,
    max_types: int,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native batched pool selection (ktpu_pool_select). Returns
    (selected row indices [F, max_rows], counts [F]; count -1 = no feasible
    row) or None when the library is unavailable."""
    lib = load()
    if lib is None:
        return None
    demand = np.ascontiguousarray(demand, dtype=np.float64)
    capacity = np.ascontiguousarray(capacity, dtype=np.float32)
    row_types = np.ascontiguousarray(row_types, dtype=np.int32)
    row_prices = np.ascontiguousarray(row_prices, dtype=np.float64)
    num_fills, dims = demand.shape
    out_rows = np.empty((num_fills, max_rows), dtype=np.int32)
    out_counts = np.empty(num_fills, dtype=np.int32)

    def ptr(array, ctype):
        return array.ctypes.data_as(ctypes.POINTER(ctype))

    lib.ktpu_pool_select(
        ptr(demand, ctypes.c_double),
        num_fills,
        dims,
        ptr(capacity, ctypes.c_float),
        ptr(row_types, ctypes.c_int),
        ptr(row_prices, ctypes.c_double),
        len(row_types),
        max_rows,
        min_rows,
        band,
        ceiling_ratio,
        max_types,
        ptr(out_rows, ctypes.c_int),
        ptr(out_counts, ctypes.c_int),
    )
    return out_rows, out_counts
