"""Column-LP mix packing: the host-overlap candidate of the cost solve.

Ref: the reference's packer greedily fills one node shape at a time
(pkg/controllers/provisioning/binpacking/packer.go:82-189) and never
revisits the *mix* of node shapes it bought. On workloads whose pod shapes
are complementary (cpu-heavy pods pairing with mem-heavy ones), a greedy
pass — even a price-aware one — leaves a few percent of projected $/hr on
the table versus jointly choosing the fill *configurations* to buy. This
module recovers that gap with a configuration LP:

  1. enumerate candidate node fills ("columns"): for a pruned set of
     price-efficient types, seed each fill with k pods of group `a`
     (k swept over fractions of the max), max-fill with group `b`, then
     top off first-fit over all groups — the classic complementary-pair
     structure the greedy pass cannot see;
  2. price each column at the cheapest pool of any instance type whose
     usable capacity dominates the column's demand (the same
     launch-realization rule the decode path applies);
  3. solve the covering LP  min c·x  s.t.  fills^T x >= counts  (scipy's
     HiGHS — a hard dependency of jax — with a greedy fallback);
  4. integerize: floor, greedily cover the residual by best
     price-per-covered-pod, trim overshoot, and clamp fills to remaining
     pods while emitting rounds so the cover is exact.

Everything here is plain numpy on the HOST, by design: the fused device
kernel's dispatch is async and its fetch pays a full device round trip
(tens of ms on a tunneled accelerator), so this entire pipeline runs in
that otherwise-idle window and adds nothing to the solve's latency
(models/solver.cost_solve_dense overlaps it with the device).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

# Enumeration budget: types kept after price-efficiency pruning, seed-group
# cap, and the ka sweep fractions. J = TYPES_BUDGET * min(G, GROUPS_CAP)^2 *
# len(KA_FRACS) columns — ~65k at the 50k-pod bench shape, a few ms of
# vectorized numpy.
TYPES_BUDGET = 64
GROUPS_CAP = 32
KA_FRACS = (1.0, 0.75, 0.5, 0.25)
_EPS = 1e-4


def _hash_mixers(num_groups: int) -> np.ndarray:
    """Deterministic odd 64-bit multipliers for fill dedup — shared by the
    native and numpy enumerations so their keys agree."""
    return (
        np.random.default_rng(0x5DEECE66D)
        .integers(1, 2**63, size=num_groups, dtype=np.uint64)
        | np.uint64(1)
    )


def _candidate_types(
    capacity: np.ndarray, pool_floor: np.ndarray
) -> np.ndarray:
    """Union of the most price-efficient types per resource dimension."""
    finite = np.isfinite(pool_floor) & (pool_floor > 0)
    dims = min(3, capacity.shape[1])
    sel: set = set()
    per_dim = max(TYPES_BUDGET // dims, 1)
    for d in range(dims):
        eff = np.where(
            finite & (capacity[:, d] > 0),
            pool_floor / np.maximum(capacity[:, d], 1e-9),
            np.inf,
        )
        sel |= set(np.argsort(eff, kind="stable")[:per_dim].tolist())
    return np.array(sorted(sel), dtype=np.int32)[:TYPES_BUDGET]


def _seed_groups(vectors: np.ndarray, counts: np.ndarray) -> np.ndarray:
    """Pair-seed groups: the GROUPS_CAP largest by normalized demand share;
    every group still participates via the top-off."""
    num_groups = vectors.shape[0]
    if num_groups <= GROUPS_CAP:
        return np.arange(num_groups, dtype=np.int32)
    load = (counts[:, None] * vectors).astype(np.float64)
    norm = load / np.maximum(load.sum(axis=0, keepdims=True), 1e-9)
    seeds = np.argsort(-norm.max(axis=1), kind="stable")[:GROUPS_CAP]
    return np.sort(seeds).astype(np.int32)


def enumerate_pair_columns(
    vectors: np.ndarray,  # [G, R] group request vectors (FFD-sorted desc)
    counts: np.ndarray,  # [G] pods per group
    capacity: np.ndarray,  # [T, R] usable capacity
    pool_floor: np.ndarray,  # [T] cheapest advertised pool price per type
) -> Tuple[np.ndarray, np.ndarray]:
    """Candidate fills [J, G] int64 (deduped) and their packed-type anchor
    [J] int32. Prefers the native enumeration (native/ffd.cc
    ktpu_mix_enumerate, ~15x the numpy fallback below — it must fit in the
    dispatch-to-fetch overlap window)."""
    num_groups = vectors.shape[0]
    cand_types = _candidate_types(capacity, pool_floor)
    if cand_types.size == 0:
        return np.zeros((0, num_groups), np.int64), np.zeros((0,), np.int32)
    seed_groups = _seed_groups(vectors, counts)
    mixers = _hash_mixers(num_groups)

    from karpenter_tpu.ops import native

    result = native.mix_enumerate(
        vectors,
        counts,
        capacity[cand_types],
        seed_groups,
        np.asarray(KA_FRACS, np.float32),
        mixers,
    )
    if result is not None:
        fills, cand_index = result
        return fills, cand_types[cand_index]
    return _enumerate_pair_columns_numpy(
        vectors, counts, capacity, cand_types, seed_groups, mixers
    )


def _enumerate_pair_columns_numpy(
    vectors: np.ndarray,
    counts: np.ndarray,
    capacity: np.ndarray,
    cand_types: np.ndarray,
    seed_groups: np.ndarray,
    mixers: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized fallback enumeration (no native toolchain)."""
    num_groups = vectors.shape[0]
    cap_t = capacity[cand_types]
    fracs = np.asarray(KA_FRACS)
    tt, aa, ff, bb = np.meshgrid(
        np.arange(len(cand_types)),
        seed_groups,
        np.arange(len(fracs)),
        seed_groups,
        indexing="ij",
    )
    tt, aa, ff, bb = (x.ravel() for x in (tt, aa, ff, bb))
    cap_j = cap_t[tt]  # [J, R]

    def max_fit(remaining: np.ndarray, vec: np.ndarray) -> np.ndarray:
        with np.errstate(divide="ignore", invalid="ignore"):
            ratio = np.where(
                vec > 0, remaining / np.where(vec > 0, vec, 1.0), np.inf
            )
        return np.maximum(np.floor(ratio.min(axis=1) + _EPS), 0.0)

    va = vectors[aa]
    ka = np.minimum(max_fit(cap_j, va), counts[aa].astype(np.float64))
    ka = np.floor(fracs[ff] * ka + 1e-9)
    remaining = cap_j - ka[:, None] * va
    vb = vectors[bb]
    kb = np.minimum(max_fit(remaining, vb), counts[bb].astype(np.float64))
    kb = np.where(aa == bb, 0.0, kb)
    remaining = remaining - kb[:, None] * vb

    fills = np.zeros((len(tt), num_groups), np.int64)
    rows = np.arange(len(tt))
    np.add.at(fills, (rows, aa), ka.astype(np.int64))
    np.add.at(fills, (rows, bb), kb.astype(np.int64))
    # First-fit top-off in group order (desc pod size, matching the FFD
    # convention) — turns every pair seed into a maximal fill.
    for g in range(num_groups):
        if counts[g] <= 0:
            continue
        n = np.minimum(
            max_fit(remaining, vectors[g]),
            (counts[g] - fills[:, g]).astype(np.float64),
        ).astype(np.int64)
        if not n.any():
            continue
        fills[:, g] += n
        remaining = remaining - n[:, None].astype(np.float64) * vectors[g]

    nonzero = fills.sum(axis=1) > 0
    fills = fills[nonzero]
    types_out = cand_types[tt[nonzero]]
    # Dedup by 64-bit hash: the ka sweep × pair grid collapses ~15x (many
    # seeds top off to the same maximal fill). Collision odds are ~J²/2⁶⁴.
    keys = (fills.astype(np.uint64) * mixers[None, :]).sum(
        axis=1, dtype=np.uint64
    )
    _, first = np.unique(keys, return_index=True)
    first = np.sort(first)
    return fills[first], types_out[first]


def price_columns(
    fills: np.ndarray,  # [J, G]
    vectors: np.ndarray,  # [G, R]
    capacity: np.ndarray,  # [T, R]
    pool_floor: np.ndarray,  # [T]
    block: int = 16,
) -> np.ndarray:
    """[J] cheapest pool price of any type whose usable capacity dominates
    each column's demand — the price the launch realization actually pays
    (demand-level dominance, sharper than full-capacity dominance).

    Types are scanned in ascending price order and each column takes the
    FIRST feasible hit (native ktpu_mix_price; block-scan numpy fallback) —
    average work is a few dozen type checks per column, not J*T*R."""
    demand = fills.astype(np.float64) @ vectors  # [J, R]
    order = np.argsort(
        np.where(np.isfinite(pool_floor), pool_floor, np.inf), kind="stable"
    )
    from karpenter_tpu.ops import native

    native_prices = native.mix_price(demand, capacity, pool_floor, order)
    if native_prices is not None:
        return native_prices
    prices = np.full(fills.shape[0], np.inf)
    unpriced = np.arange(fills.shape[0])
    for start in range(0, len(order), block):
        if unpriced.size == 0:
            break
        types_block = order[start : start + block]
        if not np.isfinite(pool_floor[types_block]).any():
            break  # the rest of the order is unpriced types
        feasible = (
            capacity[types_block][None, :, :]
            >= demand[unpriced][:, None, :] - 1e-6
        ).all(axis=2)
        hit = np.where(
            feasible, pool_floor[types_block][None, :], np.inf
        ).min(axis=1)
        prices[unpriced] = hit
        unpriced = unpriced[~np.isfinite(hit)]
    return prices


# Covering-LP column budget: HiGHS on [G, J] stays a few ms at this size.
# Deduped enumerations usually fit under it, so the reduced-cost prune is a
# backstop for pathological grids, not the normal path.
MAX_LP_COLUMNS = 4096


def aggregate_lp_bound(
    capacity: np.ndarray,  # [T, R]
    pool_floor: np.ndarray,  # [T] cheapest pool price per type
    demand: np.ndarray,  # [R] total demand
) -> Optional[Tuple[float, np.ndarray]]:
    """The aggregate fractional LP: min Σ n_t·price_t s.t. the bought
    capacity covers total demand (T variables, R constraints, ~1ms). Its
    objective lower-bounds ANY feasible plan's projected cost (bin-packing
    integrality only pushes real plans above it); its duals price each
    resource unit. Returns (objective, dual_per_resource [R]) or None.
    Shared by the column prune here and bench.py's published
    cost_ratio_lowest_price_lp_bound — one formulation, one meaning."""
    try:
        from scipy.optimize import linprog
    except Exception:  # pragma: no cover — scipy ships with jax
        return None
    result = linprog(
        np.where(np.isfinite(pool_floor), pool_floor, 1e9),
        A_ub=-capacity.T.astype(np.float64),
        b_ub=-np.asarray(demand, np.float64),
        bounds=(0, None),
        method="highs",
    )
    if not result.success or result.ineqlin is None:
        return None
    return float(result.fun), -np.asarray(result.ineqlin.marginals)


def certified_lp_floor(
    vectors: np.ndarray,  # [G, R]
    counts: np.ndarray,  # [G]
    capacity: np.ndarray,  # [T, R]
    pool_floor: np.ndarray,  # [T]
    max_rounds: int = 10,
    time_budget_s: float = 30.0,
) -> Optional[Tuple[float, bool]]:
    """The cutting-stock LP optimum with an exact-pricing certificate:
    (objective, certified).

    The aggregate LP (aggregate_lp_bound) lets fractional capacity cover
    total demand and therefore ignores per-node dimensional fragmentation —
    at mid-ladder scale it sits several points below anything buildable
    from real node fills. THIS floor is over actual columns: solve the
    covering LP on the enumeration, then column-generate with the exact
    pricing problem (per type t: max y·n s.t. n·V ≤ cap_t, n ≤ counts,
    integer — a ≤G-variable MILP via scipy HiGHS) until no column prices
    below the duals. certified=True means the LP duals admit NO improving
    feasible column anywhere in the (type, fill) space, i.e. the objective
    is the exact fractional optimum over ALL single-node fills — a valid
    lower bound on every integral plan, and an attainable one up to
    integrality (bench publishes it per ladder config as lp_bound).
    certified=False means the objective is only the LP optimum over the
    columns examined so far — an ESTIMATE that real plans can legitimately
    beat, NOT a bound (bench falls back to the aggregate bound then).
    Pricing iterates only dominance-undominated types: a type whose
    capacity is covered by a cheaper type can never price a new column.

    Runs in bench/analysis only — the ~0.1pp it adds over the enumeration
    (observed at the 10k and 50k shapes) is not worth seconds of MILP on
    the production solve path. Returns None when scipy's MILP is
    unavailable."""
    try:
        from scipy.optimize import linprog, milp  # noqa: F401 — milp gates
    except Exception:  # pragma: no cover — scipy ships with jax
        return None
    import time as _time

    counts = counts.astype(np.int64)
    fills, _ = enumerate_pair_columns(vectors, counts, capacity, pool_floor)
    if fills.shape[0] == 0:
        return None
    prices = price_columns(fills, vectors, capacity, pool_floor)
    usable = np.isfinite(prices)
    fills, prices = fills[usable], prices[usable]
    if fills.shape[0] == 0:
        return None

    # Pricing candidates: finite-priced, dominance-undominated types. A
    # type i is prunable when some OTHER finite type j has capacity >= i's
    # in every dimension at a price <= i's (ties broken by index so mutual
    # equals keep exactly one survivor): every fill feasible on i is then
    # feasible on j with reduced cost no worse, so pricing j covers i —
    # the pruning is sound for the optimality certificate.
    finite = np.isfinite(pool_floor)
    # dominates[i, j]: type j's capacity covers type i's (the convention
    # mix_candidate uses for the same matrix).
    dominates = (capacity[None, :, :] >= capacity[:, None, :] - 1e-6).all(axis=2)
    strictly_cheaper = pool_floor[None, :] < pool_floor[:, None]
    index = np.arange(len(pool_floor))
    price_tie_lower_index = (
        pool_floor[None, :] == pool_floor[:, None]
    ) & (index[None, :] < index[:, None])
    prunable = (
        dominates & finite[None, :] & (strictly_cheaper | price_tie_lower_index)
    ).any(axis=1)
    price_types = np.nonzero(finite & ~prunable)[0]

    deadline = _time.monotonic() + time_budget_s
    certified = False
    objective = None
    for _ in range(max_rounds):
        result = linprog(
            prices,
            A_ub=-fills.T.astype(np.float64),
            b_ub=-counts.astype(np.float64),
            bounds=(0, None),
            method="highs",
        )
        if not result.success or result.ineqlin is None:
            return None
        objective = float(result.fun)
        if _time.monotonic() > deadline:
            break  # uncertified: objective is an ESTIMATE, not a bound
        duals = -np.asarray(result.ineqlin.marginals)
        new_fills, exhaustive = _price_new_columns(
            duals, vectors, counts, capacity, pool_floor, price_types, deadline
        )
        if not new_fills:
            # No improving column found. That is a certificate only when
            # every pricing subproblem was solved to proven optimality
            # within the deadline.
            certified = exhaustive
            break
        stacked = np.stack(new_fills)
        new_prices = price_columns(stacked, vectors, capacity, pool_floor)
        priced = np.isfinite(new_prices)
        fills = np.concatenate([fills, stacked[priced]])
        prices = np.concatenate([prices, new_prices[priced]])
    if objective is None:
        return None
    return objective, certified


def _price_new_columns(
    duals: np.ndarray,
    vectors: np.ndarray,
    counts: np.ndarray,
    capacity: np.ndarray,
    pool_floor: np.ndarray,
    price_types: np.ndarray,
    deadline: float,
) -> Tuple[List[np.ndarray], bool]:
    """Exact pricing step of certified_lp_floor: per candidate type, solve
    max duals·n s.t. n·V ≤ cap_t, n ≤ counts, integer (≤G-variable MILP)
    and return (improving fills, exhaustive). exhaustive=True means every
    pricing subproblem was solved to PROVEN optimality before the deadline
    — only then does an empty fill list certify the LP optimal over the
    complete column space. Each MILP gets the remaining wall budget as its
    time_limit; a time-limited incumbent can still contribute a column but
    voids exhaustiveness."""
    import time as _time

    from scipy.optimize import Bounds, LinearConstraint, milp

    active = np.nonzero((duals > 1e-12) & (counts > 0))[0]
    if active.size == 0:
        return [], True
    new_fills: List[np.ndarray] = []
    exhaustive = True
    for t in price_types:
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            exhaustive = False
            break
        pricing = milp(
            c=-duals[active],
            constraints=LinearConstraint(
                vectors[active].T, ub=capacity[t].astype(np.float64)
            ),
            bounds=Bounds(0, counts[active].astype(np.float64)),
            integrality=np.ones(active.size),
            options={"time_limit": max(remaining, 0.1)},
        )
        if pricing.status == 1:  # hit the iteration/time limit: not proven
            exhaustive = False
        if pricing.x is None:
            continue
        value = float(duals[active] @ pricing.x)
        if pool_floor[t] - value < -1e-7:
            fill = np.zeros(vectors.shape[0], np.int64)
            fill[active] = np.round(pricing.x).astype(np.int64)
            new_fills.append(fill)
    return new_fills, exhaustive


def _prune_columns(
    fills: np.ndarray,
    types: np.ndarray,
    prices: np.ndarray,
    vectors: np.ndarray,
    counts: np.ndarray,
    capacity: np.ndarray,
    pool_floor: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Keep the MAX_LP_COLUMNS most promising columns by reduced cost
    against the aggregate LP's resource duals (aggregate_lp_bound). The
    duals price each resource unit; a column whose dual value most exceeds
    its price is the kind the covering LP will buy."""
    if fills.shape[0] <= MAX_LP_COLUMNS:
        return fills, types, prices
    demand = (counts[:, None] * vectors).sum(axis=0)
    bound = aggregate_lp_bound(capacity, pool_floor, demand)
    value = None
    if bound is not None:
        group_value = vectors @ bound[1]  # [G]
        value = fills @ group_value  # [J]
    if value is None:
        # No dual estimate: fall back to pods-covered per dollar.
        with np.errstate(divide="ignore"):
            value = fills.sum(axis=1) / np.maximum(prices, 1e-12)
        keep = np.argsort(-value, kind="stable")[:MAX_LP_COLUMNS]
    else:
        keep = np.argsort(prices - value, kind="stable")[:MAX_LP_COLUMNS]
    return fills[keep], types[keep], prices[keep]


def solve_cover_lp(
    fills: np.ndarray, prices: np.ndarray, counts: np.ndarray
) -> Optional[np.ndarray]:
    """Fractional covering LP via scipy HiGHS (a jax hard dependency);
    None when unavailable or infeasible — callers fall back to pure greedy
    integerization from x=0."""
    try:
        from scipy.optimize import linprog
    except Exception:  # pragma: no cover — scipy ships with jax
        return None
    result = linprog(
        prices,
        A_ub=-fills.T.astype(np.float64),
        b_ub=-counts.astype(np.float64),
        bounds=(0, None),
        method="highs",
    )
    if not result.success:
        return None
    return result.x


def integerize_cover(
    fills: np.ndarray,  # [J, G]
    prices: np.ndarray,  # [J]
    x_frac: Optional[np.ndarray],
    counts: np.ndarray,  # [G]
) -> Optional[np.ndarray]:
    """Integral node counts per column covering `counts`: floor the LP,
    greedily cover the residual by price per covered pod, then trim
    overshoot off the most expensive columns. Returns [J] int64 or None
    when some pods cannot be covered by any column."""
    num_cols = fills.shape[0]
    if num_cols == 0:
        return None
    cover_matrix = fills.astype(np.int64)
    x = (
        np.floor(x_frac + 1e-9).astype(np.int64)
        if x_frac is not None
        else np.zeros(num_cols, np.int64)
    )
    residual = np.maximum(counts - cover_matrix.T @ x, 0)
    while residual.sum() > 0:
        covered = np.minimum(cover_matrix, residual[None, :]).sum(axis=1)
        with np.errstate(divide="ignore"):
            score = np.where(covered > 0, prices / covered, np.inf)
        j = int(np.argmin(score))
        if not np.isfinite(score[j]):
            return None  # residual pods fit no column
        fill_j = cover_matrix[j]
        with np.errstate(divide="ignore"):
            repl = int(
                np.min(
                    np.where(
                        fill_j > 0,
                        residual // np.maximum(fill_j, 1),
                        np.iinfo(np.int64).max,
                    )
                )
            )
        repl = max(repl, 1)
        x[j] += repl
        residual = np.maximum(residual - repl * fill_j, 0)
    # Trim overshoot, most expensive used columns first.
    slack = cover_matrix.T @ x - counts
    used = np.nonzero(x)[0]
    for j in used[np.argsort(-prices[used], kind="stable")]:
        fill_j = cover_matrix[j]
        with np.errstate(divide="ignore"):
            removable = np.min(
                np.where(
                    fill_j > 0,
                    slack // np.maximum(fill_j, 1),
                    np.iinfo(np.int64).max,
                )
            )
        k = int(min(x[j], max(removable, 0)))
        if k > 0:
            x[j] -= k
            slack -= k * fill_j
    return x


def mix_candidate(
    vectors: np.ndarray,
    counts: np.ndarray,  # [G] SOLVABLE pods per group (infeasible zeroed)
    capacity: np.ndarray,
    pool_floor: np.ndarray,  # [T] cheapest advertised pool price
    extra_columns: Optional[
        List[Tuple[int, np.ndarray]]
    ] = None,  # (type, fill) seeds, e.g. the kernel candidates' rounds
) -> Optional[List[Tuple[int, np.ndarray, int]]]:
    """The full column-LP pipeline → round list [(type, fill, repl)], with
    fills clamped to remaining pods so coverage is exact (decode walks group
    cursors and must never overrun). None when no plan covers the counts."""
    counts = counts.astype(np.int64)
    if counts.sum() == 0 or capacity.shape[0] == 0:
        return None
    fills, types = enumerate_pair_columns(vectors, counts, capacity, pool_floor)
    if fills.shape[0]:
        # Prune on COARSE prices first (type-capacity dominance, one [T, T]
        # reduction), then exact-price only the survivors — exact
        # demand-dominance pricing over the full enumeration would dominate
        # the pipeline's runtime.
        dominates = (
            capacity[None, :, :] >= capacity[:, None, :] - 1e-6
        ).all(axis=2)
        effective = np.where(dominates, pool_floor[None, :], np.inf).min(axis=1)
        coarse = effective[types]
        usable = np.isfinite(coarse)
        fills, types, coarse = fills[usable], types[usable], coarse[usable]
        fills, types, _ = _prune_columns(
            fills, types, coarse, vectors, counts, capacity, pool_floor
        )
        prices = price_columns(fills, vectors, capacity, pool_floor)
        usable = np.isfinite(prices)
        fills, types, prices = fills[usable], types[usable], prices[usable]
    else:
        prices = np.zeros((0,))
    # Rescue columns: one single-group max-fill per group on its cheapest
    # feasible type — guarantees every solvable group is coverable even when
    # its only feasible types fell outside the pruned enumeration set.
    # Appended AFTER pruning (with caller seeds) so they always survive.
    rescue: List[Tuple[int, np.ndarray]] = []
    for g in range(vectors.shape[0]):
        if counts[g] <= 0:
            continue
        vec = vectors[g]
        feasible = (capacity >= vec[None, :] - 1e-6).all(axis=1)
        priced = np.where(feasible, pool_floor, np.inf)
        t = int(np.argmin(priced))
        if not np.isfinite(priced[t]):
            # Feasible but unpriced type (no offering): still usable as a
            # coverage column — fall back to any feasible type.
            feasible_idx = np.nonzero(feasible)[0]
            if feasible_idx.size == 0:
                continue
            t = int(feasible_idx[0])
        with np.errstate(divide="ignore", invalid="ignore"):
            ratio = np.where(
                vec > 0, capacity[t] / np.where(vec > 0, vec, 1.0), np.inf
            )
        k = int(
            min(max(np.floor(ratio.min() + _EPS), 1.0), float(counts[g]))
        )
        fill = np.zeros(vectors.shape[0], np.int64)
        fill[g] = k
        rescue.append((t, fill))
    extras = list(extra_columns or []) + rescue
    if extras:
        seed_fills = np.stack([np.asarray(f, np.int64) for _, f in extras])
        seed_types = np.asarray([t for t, _ in extras], np.int32)
        seed_prices = price_columns(seed_fills, vectors, capacity, pool_floor)
        usable = np.isfinite(seed_prices)
        fills = (
            np.concatenate([fills, seed_fills[usable]])
            if fills.size
            else seed_fills[usable]
        )
        types = (
            np.concatenate([types, seed_types[usable]])
            if types.size
            else seed_types[usable]
        )
        prices = (
            np.concatenate([prices, seed_prices[usable]])
            if prices.size
            else seed_prices[usable]
        )
    if fills.shape[0] == 0:
        return None
    x = integerize_cover(
        fills, prices, solve_cover_lp(fills, prices, counts), counts
    )
    if x is None:
        return None

    # Emit rounds cheapest-first, clamping to remaining pods: expensive
    # columns absorb the trim, and coverage comes out exact (the integral x
    # covers counts per group, and clamping only drops pods a group no
    # longer needs, so the walk always drains `remaining` to zero).
    remaining = counts.copy()
    rounds: List[Tuple[int, np.ndarray, int]] = []
    used = np.nonzero(x)[0]
    for j in used[np.argsort(prices[used], kind="stable")]:
        budget = int(x[j])
        fill = fills[j]
        while budget > 0 and remaining.sum() > 0:
            clamped = np.minimum(fill, remaining)
            if clamped.sum() == 0:
                break
            if np.array_equal(clamped, fill):
                with np.errstate(divide="ignore"):
                    full = int(
                        np.min(
                            np.where(
                                fill > 0,
                                remaining // np.maximum(fill, 1),
                                np.iinfo(np.int64).max,
                            )
                        )
                    )
                take = min(budget, max(full, 1))
                rounds.append((int(types[j]), fill.copy(), take))
                remaining -= take * fill
                budget -= take
            else:
                rounds.append((int(types[j]), clamped.copy(), 1))
                remaining -= clamped
                budget -= 1
    if remaining.sum() != 0:
        return None  # defensive: exact cover failed
    return rounds
