"""Greedy First-Fit-Decreasing packer — the host-side baseline and fallback.

Behaviorally faithful to the reference kernel
(ref: pkg/controllers/provisioning/binpacking/packer.go:82-189 and
packable.go:113-175) but reformulated over *pod groups* (identical request
vectors) instead of individual pods, which is exact for FFD because identical
pods are adjacent in the sorted order. This is both the correctness oracle the
TPU kernels are cross-checked against and the in-process fallback when no
accelerator is available.

Reference semantics preserved:
  - pods sorted desc by cpu then memory; packables sorted asc.
  - per node: greedy fill; if the largest remaining pod doesn't fit, the
    packable packs nothing; early exit once remaining capacity drops to/below
    the smallest remaining pod on any nonzero dimension (packable.go:120,147-157
    — including its quirk of exiting even when the smallest pod would fit
    exactly).
  - per round: the largest packable sets the max-pods upper bound; the first
    (smallest) packable achieving that bound wins, and it plus the next
    MAX_INSTANCE_TYPES-1 larger packables become the node's instance options
    (packer.go:163-189).
  - a largest pod that fits nowhere is set aside as unschedulable
    (packer.go:120-124).
  - packings with identical instance-type options merge into one entry with
    node_quantity += 1 (packer.go:126-135 hashes with Pods ignored).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider import InstanceType
from karpenter_tpu.ops.encode import InstanceFleet, PodGroups, build_fleet, group_pods

# Number of instance-type options offered to the cloud provider per node
# (ref: packer.go:38-39 — EC2 Fleet request-size bound).
MAX_INSTANCE_TYPES = 20


@dataclass
class PoolOption:
    """One (type, zone) launch-override row with an explicit priority.

    The reference's override rows carry a priority only per *type* (its index
    in the ascending-size window, instance.go:173-207) and are therefore
    price-blind within a type across zones. A cost-aware plan ranks individual
    pools by price instead — same row budget, strictly more control."""

    instance_type: InstanceType
    zone: str
    price: float
    priority: int


class LazyNodePods:
    """Per-node pod lists materialized on first access.

    Distributing 50k PodSpec refs into per-node lists costs tens of ms of
    pure Python; the solve boundary only needs the *plan* (fills, counts,
    options). Segments record (replication, [(group, start, n)]) windows over
    groups.members — integer bookkeeping at decode time — and the concrete
    lists are built lazily when the bind path (or a test) iterates them.
    Within a replicated segment node k takes members[g][start+k*n : start+(k+1)*n],
    matching the eager decode's sequential cursor order exactly."""

    def __init__(self, members):
        self._members = members
        self._segments: List[Tuple[int, List[Tuple[int, int, int]]]] = []
        self._cache: Optional[List[List[PodSpec]]] = None

    def add_segment(self, repl: int, slices: List[Tuple[int, int, int]]) -> None:
        self._segments.append((repl, slices))
        self._cache = None

    def _materialize(self) -> List[List[PodSpec]]:
        if self._cache is None:
            nodes: List[List[PodSpec]] = []
            for repl, slices in self._segments:
                for k in range(repl):
                    node: List[PodSpec] = []
                    for g, start, n in slices:
                        node.extend(
                            self._members[g][start + k * n : start + (k + 1) * n]
                        )
                    nodes.append(node)
            self._cache = nodes
        return self._cache

    def __len__(self) -> int:
        return sum(repl for repl, _ in self._segments)

    def __iter__(self):
        return iter(self._materialize())

    def __getitem__(self, index):
        return self._materialize()[index]

    def __eq__(self, other):
        try:
            return list(self) == list(other)
        except TypeError:
            return NotImplemented


@dataclass
class Packing:
    """One node shape: pods per node, viable instance types, node count.

    pods_per_node is a plain list on the eager path (pack_groups) and a
    LazyNodePods on solver-decoded packings — consumers iterate/len/index,
    they don't mutate."""

    pods_per_node: "Sequence[List[PodSpec]]"
    instance_type_options: List[InstanceType]
    node_quantity: int = 1
    # Cost-aware plans additionally pin pool-level override rows (cheapest
    # first). None = reference semantics (derive rows from
    # instance_type_options x offered zones, priority per type).
    pool_options: Optional[List[PoolOption]] = None
    # Constrained plans may stamp extra labels on every node of this packing
    # (custom-key topology domains realize as labels at registration —
    # constraints/solve.decode_constrained); None = no extra labels.
    node_labels: Optional[dict] = None

    @property
    def pods(self) -> List[PodSpec]:
        return [pod for node in self.pods_per_node for pod in node]


@dataclass
class PackResult:
    packings: List[Packing]
    unschedulable: List[PodSpec] = field(default_factory=list)

    @property
    def node_count(self) -> int:
        return sum(p.node_quantity for p in self.packings)

    def projected_cost(self) -> float:
        """$/hr if each node launches as its cheapest offered option."""
        total = 0.0
        for p in self.packings:
            if p.pool_options:
                price = min(pool.price for pool in p.pool_options)
            else:
                price = min(
                    (it.min_price() for it in p.instance_type_options),
                    default=float("inf"),
                )
            total += p.node_quantity * price
        return total


def fill_node(
    capacity: np.ndarray,
    total: np.ndarray,
    vectors: np.ndarray,
    counts: np.ndarray,
    quirk: bool = True,
) -> np.ndarray:
    """Greedily fill one node. Returns packed count per group.

    `capacity` is the usable ledger (total - overhead - daemons); `total` is
    the raw instance capacity used by the early-exit check, matching
    packable.go fits() comparing against p.total. quirk=False disables the
    reference's fits() early exit (pure greedy — used by the cost paths,
    which don't need bit-parity and pack strictly better).
    """
    num_groups = vectors.shape[0]
    packed = np.zeros(num_groups, dtype=np.int64)
    active = np.nonzero(counts > 0)[0]
    if active.size == 0:
        return packed
    smallest = vectors[active[-1]]
    remaining = capacity.astype(np.float64).copy()
    packed_any = False
    for g in active:
        need = vectors[g].astype(np.float64)
        positive = need > 0
        if positive.any():
            n_fit = int(np.floor((remaining[positive] / need[positive]).min() + 1e-9))
        else:
            n_fit = int(counts[g])
        n = min(int(counts[g]), max(n_fit, 0))
        if n > 0:
            packed[g] = n
            remaining -= need * n
            packed_any = True
        if n < counts[g]:
            # This group's next pod failed to reserve.
            if not packed_any:
                return np.zeros(num_groups, dtype=np.int64)  # largest pod set aside
            # Early exit when essentially full w.r.t. the smallest pod:
            # reserved + smallest >= total on any tracked dim (fits(), :147-157).
            if quirk and np.any((total > 0) & (remaining <= smallest + 1e-9)):
                break
    return packed


def _pack_with_largest(
    fleet: InstanceFleet, vectors: np.ndarray, counts: np.ndarray
) -> Tuple[Optional[np.ndarray], List[InstanceType]]:
    """One round: pick the node that packs the max pods achievable by the
    largest packable, preferring the smallest instance type that achieves it
    (ref: packer.go:163-189). Returns (packed counts, instance options)."""
    last = fleet.num_types - 1
    upper = fill_node(fleet.capacity[last], fleet.total[last], vectors, counts)
    max_packed = int(upper.sum())
    if max_packed == 0:
        return None, []
    for t in range(fleet.num_types):
        packed = (
            upper
            if t == last
            else fill_node(fleet.capacity[t], fleet.total[t], vectors, counts)
        )
        if int(packed.sum()) == max_packed:
            options = fleet.instance_types[t : t + MAX_INSTANCE_TYPES]
            return packed, options
    raise AssertionError("largest packable must achieve its own bound")


def pack_groups(fleet: InstanceFleet, groups: PodGroups) -> PackResult:
    """Drive rounds of _pack_with_largest until all pods are placed or set
    aside (ref: packer.go Pack:105-137)."""
    counts = groups.counts.astype(np.int64).copy()
    # Cursor into each group's member list for assigning concrete pods.
    cursors = [0] * groups.num_groups
    by_options: dict = {}
    packings: List[Packing] = []
    unschedulable: List[PodSpec] = []

    if fleet.num_types == 0:
        for g in range(groups.num_groups):
            unschedulable.extend(groups.members[g])
        return PackResult(packings=[], unschedulable=unschedulable)

    while counts.sum() > 0:
        packed, options = _pack_with_largest(fleet, groups.vectors, counts)
        if packed is None:
            # Largest remaining pod fits nowhere: set it aside.
            g = int(np.nonzero(counts > 0)[0][0])
            unschedulable.append(groups.members[g][cursors[g]])
            cursors[g] += 1
            counts[g] -= 1
            continue
        node_pods: List[PodSpec] = []
        for g in np.nonzero(packed > 0)[0]:
            n = int(packed[g])
            node_pods.extend(groups.members[g][cursors[g] : cursors[g] + n])
            cursors[g] += n
            counts[g] -= n
        key = tuple(it.name for it in options)
        existing = by_options.get(key)
        if existing is not None:
            existing.node_quantity += 1
            existing.pods_per_node.append(node_pods)
        else:
            packing = Packing(pods_per_node=[node_pods], instance_type_options=list(options))
            by_options[key] = packing
            packings.append(packing)
    return PackResult(packings=packings, unschedulable=unschedulable)


def pack_rounds_dense(
    vectors: np.ndarray,
    counts: np.ndarray,
    capacity: np.ndarray,
    total: np.ndarray,
    quirk: bool = True,
) -> Tuple[List[Tuple[int, np.ndarray, int]], np.ndarray]:
    """pack_groups' round loop on bare arrays — (rounds, unschedulable counts)
    in the decode format the TPU kernel and native packer emit. This is the
    object-free last-resort path for the solver sidecar, which holds tensors
    off the wire and no PodSpec/InstanceType objects."""
    counts = counts.astype(np.int64).copy()
    num_groups, num_types = int(vectors.shape[0]), int(capacity.shape[0])
    rounds: List[Tuple[int, np.ndarray, int]] = []
    unschedulable = np.zeros(num_groups, dtype=np.int64)
    if num_types == 0:
        unschedulable += counts
        return rounds, unschedulable
    last = num_types - 1
    while counts.sum() > 0:
        upper = fill_node(capacity[last], total[last], vectors, counts, quirk=quirk)
        max_packed = int(upper.sum())
        if max_packed == 0:
            g = int(np.nonzero(counts > 0)[0][0])
            unschedulable[g] += 1
            counts[g] -= 1
            continue
        for t in range(num_types):
            packed = (
                upper
                if t == last
                else fill_node(capacity[t], total[t], vectors, counts, quirk=quirk)
            )
            if int(packed.sum()) == max_packed:
                rounds.append((t, packed.astype(np.int64), 1))
                counts -= packed
                break
    return rounds, unschedulable


def pack(
    pods: Sequence[PodSpec],
    instance_types: Sequence[InstanceType],
    constraints: Constraints,
    daemons: Sequence[PodSpec] = (),
) -> PackResult:
    """The full greedy path: filter/densify the fleet, group + sort pods, pack."""
    groups = group_pods(list(pods))
    fleet = build_fleet(instance_types, constraints, pods, daemons)
    return pack_groups(fleet, groups)
