"""Runtime: the controller-manager process shell.

Ref: cmd/controller/main.go + pkg/controllers/manager.go — wires cluster
watches to reconcile loops, runs the per-Provisioner batch windows, serves
/metrics and /healthz//readyz, and holds a leader lock. Everything is
thread-based (the reference's goroutines) over the in-memory cluster store;
tests keep driving controllers synchronously without any of this.
"""

from __future__ import annotations

import heapq
import http.server
import json
import random
import threading
from typing import Callable, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.consolidation import ConsolidationController
from karpenter_tpu.controllers.counter import CounterController
from karpenter_tpu.controllers.health import HealthController
from karpenter_tpu.controllers.metrics import MetricsController, POLL_SECONDS
from karpenter_tpu.controllers.node import NodeController
from karpenter_tpu.controllers.instancegc import InstanceGcController
from karpenter_tpu.controllers.interruption import InterruptionController
from karpenter_tpu.controllers.market import MarketController
from karpenter_tpu.controllers.podgc import PodGcController
from karpenter_tpu.controllers.provisioning import (
    BATCH_IDLE_SECONDS,
    ProvisioningController,
)
from karpenter_tpu.controllers.selection import SelectionController
from karpenter_tpu.controllers.termination import TerminationController
from karpenter_tpu.models.solver import (
    CostSolver,
    GreedySolver,
    NativeSolver,
    TPUSolver,
)
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.backoff import jittered_s
from karpenter_tpu.utils.crashpoints import crashpoint
from karpenter_tpu.utils.fence import WriteFence, bind_thread
from karpenter_tpu.utils.metrics import REGISTRY
from karpenter_tpu.utils.obs import OBS, RECORDER, stacks_snapshot
from karpenter_tpu.utils.options import Options

# Reconcile-loop health metrics, mirroring what the reference's controllers
# dashboard graphs (grafana-dashboards/karpenter-controllers.json reads
# workqueue_depth, controller_runtime_reconcile_total, and the reconcile
# duration histogram that controller-runtime exports for every controller).
WORKQUEUE_DEPTH = REGISTRY.gauge(
    "workqueue_depth", "Items queued per reconcile loop", ["name"]
)
RECONCILE_TOTAL = REGISTRY.counter(
    "reconcile_total",
    "Reconciles per loop by outcome (success|requeue|error)",
    ["controller", "result"],
)
RECONCILE_DURATION = REGISTRY.histogram(
    "reconcile_time_seconds", "Reconcile latency per loop", ["controller"]
)
# Degradation oracle for the chaos storms (docs/design/chaos.md): a sweep
# that fails keeps its loop thread alive and re-enters after backoff — this
# series is how operators see the degradation (by exception class), and how
# `make chaos-smoke` proves the loops absorbed it.
SWEEP_FAILURES_TOTAL = REGISTRY.counter(
    "sweep_failures_total",
    "Failed reconcile sweeps by loop and exception class",
    ["controller", "reason"],
)
# Leader-election health (docs/operations.md HA runbook): transitions count
# observed generation bumps (a handoff — alert on a flapping rate), and the
# takeover histogram is the campaign wait from first refused CAS to the win
# (the availability gap a standby actually closes). The fence-rejection
# counter lives with the fence itself (utils/fence.py).
LEADER_TRANSITIONS_TOTAL = REGISTRY.counter(
    "leader_transitions_total", "Observed lease-generation bumps (handoffs)"
)
LEADER_TAKEOVER_SECONDS = REGISTRY.histogram(
    "leader_takeover_seconds",
    "Campaign wait from first refused lease CAS to acquisition",
)


class ReconcileLoop:
    """A keyed reconcile queue with delayed requeue — the controller-runtime
    workqueue analogue. reconcile(key) returns None (done) or a delay in
    seconds to requeue."""

    def __init__(
        self,
        name: str,
        reconcile: Callable,
        concurrency: int = 1,
        chunk: int = 1,
        fence: Optional[WriteFence] = None,
    ):
        self.name = name
        self.reconcile = reconcile
        self.concurrency = concurrency
        # The cluster's write fence, bound to each worker thread so the
        # crashpoint abort gate (utils/fence.py) can kill a deposed leader's
        # in-flight sweep at its next commit point.
        self.fence = fence
        # Keys popped per wake-up. The default 1 preserves strict one-at-a-
        # time dispatch (right for loops whose reconciles block on RPCs);
        # CPU-bound high-volume loops (selection) set it higher so a pod
        # storm costs one queue/metric lock round per CHUNK keys instead of
        # per key — at 128 workers the per-key locking convoyed the whole
        # pipeline (bench_pod_storm, sampled).
        self.chunk = max(1, chunk)
        self.log = klog.named(name)
        # Wake coalescing for chunked pools (guarded by _cv): _waiting
        # counts workers inside cv.wait(). A notify is needed ONLY when
        # every worker is waiting — any non-waiting worker re-checks the
        # heap under the cv before it can sleep, so it picks up new keys
        # without a wake (the counter window is race-free: enqueue holds
        # the cv, and a worker not counted as waiting is by definition on
        # its way to that re-check). Waking a thread per enqueue at high
        # concurrency is pure context-switch churn (sampled as the top
        # residual cost of the 128-thread pod storm). chunk=1 loops keep
        # per-key notifies: their reconciles block on RPCs, where per-key
        # parallelism is the point.
        # Per-key consecutive-failure streaks for the error backoff. A key
        # CAN be reconciled by two workers at once (a watch-event enqueue
        # during an in-flight reconcile re-queues it, and a second worker
        # may pop it before the first finishes), so the read-modify-write
        # must hold the cv lock or increments race.
        self._err_streak: dict = {}  # vet: guarded-by(self._cv)
        self._waiting = 0  # vet: guarded-by(self._cv)
        self._pops = 0  # vet: guarded-by(self._cv) — chunk pops ever (start()'s grabbed-work escape)
        self._heap: list = []  # vet: guarded-by(self._cv) — (due_time, seq, key)
        self._queued: set = set()  # vet: guarded-by(self._cv)
        self._due: dict = {}  # vet: guarded-by(self._cv) — key -> earliest pending due time
        self._cv = threading.Condition()
        self._seq = 0
        self._stop = False
        self._threads: list = []

    def enqueue(self, key, delay: float = 0.0) -> None:
        import time as _time

        if delay == 0.0:
            # Lock-free duplicate suppression (dict reads are GIL-atomic): a
            # key already queued and due NOW covers this enqueue entirely.
            # Safe against the pop race — cache writes happen BEFORE the
            # watch notify that lands here, so a worker that pops the key
            # concurrently still reconciles state at least as new as the
            # event's. Bind fan-out storms re-enqueue the same few node keys
            # tens of thousands of times; this keeps them off the lock.
            due = self._due.get(key)  # vet: unguarded(GIL-atomic dict read; rationale above)
            if due is not None and due <= _time.monotonic():
                return
        with self._cv:
            if self._enqueue_locked(key, delay, _time.monotonic()):
                WORKQUEUE_DEPTH.set(len(self._queued), self.name)
                self._notify_locked(1)

    def enqueue_many(self, pairs) -> None:
        """Enqueue a batch of (key, delay) under ONE lock round — the
        chunked reconcile loop requeues every key of a chunk at once, and
        per-key locking here was the top contention point of a 128-thread
        pod storm (sampled)."""
        import time as _time

        if not pairs:
            return
        with self._cv:
            now = _time.monotonic()
            added = 0
            for key, delay in pairs:
                added += 1 if self._enqueue_locked(key, delay, now) else 0
            if added:
                WORKQUEUE_DEPTH.set(len(self._queued), self.name)
                self._notify_locked(added)

    def _notify_locked(self, added: int) -> None:
        """Wake waiters for `added` new entries (caller holds _cv). Chunked
        pools notify only when the whole pool is asleep: any awake worker
        re-checks the heap before sleeping and drains every due key up to
        its chunk, so it collects these entries without a wake."""
        if self.chunk == 1:
            self._cv.notify(min(added, self.concurrency))
        elif self._waiting >= len(self._threads):
            # Empty _threads (pre-start enqueue) compares 0 >= 0: notify is
            # a harmless no-op and the seeding path stays unsurprising.
            self._cv.notify()

    def _enqueue_locked(self, key, delay: float, now: float) -> bool:
        """Insert under the held cv. An entry already due at-or-before this
        one covers it; an EARLIER enqueue (e.g. a watch event while the key
        sits in a long backoff) pulls the work forward, like workqueue.Add
        during rate-limited backoff — the old entry is lazily dropped when
        it pops."""
        due = now + delay
        if key in self._queued and due >= self._due.get(key, float("inf")):
            return False
        self._queued.add(key)
        self._due[key] = due
        self._seq += 1
        heapq.heappush(self._heap, (due, self._seq, key))
        return True

    def start(self) -> None:
        import time as _time

        for i in range(self.concurrency):
            thread = threading.Thread(
                target=self._run, name=f"{self.name}-{i}", daemon=True
            )
            thread.start()
            self._threads.append(thread)
        # Wait for the pool to park (every worker in cv.wait) before
        # declaring the loop started: a high-concurrency pool's boot
        # stampede — N fresh threads racing through the cv for the first
        # time — otherwise lands on top of the first real traffic. Bounded
        # wait; a pool that grabbed real work immediately is also "ready"
        # (_pops counts chunk pops, so consumed-and-emptied work still
        # satisfies the escape instead of spinning out the full deadline).
        deadline = _time.monotonic() + 1.0
        while _time.monotonic() < deadline:
            with self._cv:
                if self._waiting >= self.concurrency or self._pops:
                    break
            _time.sleep(0.001)

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()

    def _run(self) -> None:
        import time as _time

        if self.fence is not None:
            bind_thread(self.fence)
        while True:
            with self._cv:
                while not self._stop and (
                    not self._heap or self._heap[0][0] > _time.monotonic()
                ):
                    timeout = (
                        self._heap[0][0] - _time.monotonic() if self._heap else None
                    )
                    self._waiting += 1
                    try:
                        self._cv.wait(timeout=timeout)
                    finally:
                        self._waiting -= 1
                if self._stop:
                    return
                keys = self._pop_due_locked()
            if keys:
                self._reconcile_chunk(keys)

    def _pop_due_locked(self) -> list:
        """Pop every due key up to the chunk budget in one lock round
        (caller holds _cv); stale heap entries (superseded by an earlier
        enqueue) are dropped without consuming budget."""
        import time as _time

        keys = []
        now = _time.monotonic()
        while self._heap and self._heap[0][0] <= now and len(keys) < self.chunk:
            popped_due, _, key = heapq.heappop(self._heap)
            if key not in self._queued or self._due.get(key) != popped_due:
                continue  # superseded by an earlier enqueue: stale entry
            self._queued.discard(key)
            self._due.pop(key, None)
            keys.append(key)
        if keys:
            self._pops += 1
        WORKQUEUE_DEPTH.set(len(self._queued), self.name)
        return keys

    # Error-requeue backoff: a key whose reconcile keeps failing (an API
    # outage, a poisoned object) re-enters at 2^n seconds up to the cap —
    # the loop thread stays alive and the key keeps probing, but a
    # persistent fault can't hot-loop the controller against a degraded
    # apiserver. Any success resets the streak; a watch event pulls the
    # key forward early (enqueue with delay 0 supersedes a backoff entry).
    ERROR_BACKOFF_BASE_S = 1.0
    ERROR_BACKOFF_CAP_S = 30.0

    def _error_backoff_s(self, key) -> float:
        from karpenter_tpu.utils.backoff import capped_backoff_s

        with self._cv:
            streak = self._err_streak.get(key, 0) + 1
            self._err_streak[key] = streak
        return capped_backoff_s(
            self.ERROR_BACKOFF_BASE_S, self.ERROR_BACKOFF_CAP_S, streak
        )

    def forget(self, key) -> None:
        """Drop a terminally deleted key's backoff streak. The streak is
        only ever popped on a SUCCESSFUL reconcile — a key that erred its
        way out of existence (deleted mid-outage) would otherwise hold its
        entry forever, one leak per churned pod/node over a long soak. The
        pending queue entry (if any) self-heals: the key pops, reconciles
        to a not-found no-op, and leaves no state behind."""
        with self._cv:
            self._err_streak.pop(key, None)

    def err_streak_size(self) -> int:
        """Soak-oracle accessor: backoff entries currently held."""
        with self._cv:
            return len(self._err_streak)

    def _reconcile_chunk(self, keys: list) -> None:
        """Reconcile a popped chunk; metrics are recorded once per chunk
        (per-key durations, batched) so high-concurrency pools don't convoy
        on the registry locks."""
        import time as _time

        durations = []
        outcomes = {"success": 0, "requeue": 0, "error": 0}
        requeues = []
        for key in keys:
            began = _time.perf_counter()
            try:
                result = self.reconcile(key)
                outcomes["requeue" if result is not None else "success"] += 1
                with self._cv:
                    self._err_streak.pop(key, None)
            except Exception as error:  # noqa: BLE001 — must not kill the loop
                self.log.exception("reconcile %r failed", key)
                result = self._error_backoff_s(key)
                outcomes["error"] += 1
                SWEEP_FAILURES_TOTAL.inc(self.name, type(error).__name__)
            durations.append(_time.perf_counter() - began)
            if result is not None:
                requeues.append((key, float(result)))
        RECONCILE_DURATION.observe_many(durations, self.name)
        for outcome, count in outcomes.items():
            if count:
                RECONCILE_TOTAL.inc(self.name, outcome, amount=count)
        self.enqueue_many(requeues)


class LeaderElector:
    """Lease-based leader election over the cluster store
    (ref: cmd/controller/main.go:80-81 — controller-runtime leader election
    on a coordination.k8s.io Lease). One candidate holds a named lease and
    renews it at RENEW_SECONDS; rivals CAS-acquire and win only after the
    holder's LEASE_SECONDS expire without renewal. Losing a held lease (e.g.
    a renewal pause longer than the TTL) fires on_lost — production wiring
    stops the manager, matching the reference's exit-on-lost-lease.

    Scope note: mutual exclusion spans exactly the processes sharing this
    Cluster store. Over the in-memory store that is one process (the chart
    pins replicas=1); an apiserver-backed store extends it cluster-wide."""

    LEASE_NAME = "karpenter-tpu-leader"
    LEASE_SECONDS = 15.0
    RENEW_SECONDS = 5.0

    def __init__(self, cluster, identity: str, on_lost=None, rng=None):
        self.cluster = cluster
        self.identity = identity
        self.on_lost = on_lost
        self.is_leader = threading.Event()
        # The lease generation (its transitions counter) captured at
        # acquire — the fencing token. None until the first win.
        self.generation: Optional[int] = None
        # Renew/campaign waits are jittered (utils/backoff.jittered_s) so
        # replicas sharing the 5s cadence don't CAS the lease in lockstep;
        # tests inject a seeded rng.
        self._rng = rng if rng is not None else random.Random()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_renew: Optional[float] = None
        # Stamped at the first refused CAS of a campaign; a win after a
        # non-None stamp is a TAKEOVER (someone held the lease when we
        # started wanting it) and observes leader_takeover_seconds.
        self._campaign_began: Optional[float] = None

    def try_acquire(self) -> bool:
        won = self.cluster.acquire_lease(
            self.LEASE_NAME, self.identity, self.LEASE_SECONDS
        )
        now = self.cluster.clock.now()
        if not won:
            if self._campaign_began is None:
                self._campaign_began = now
            return False
        generation = int(won)
        self._last_renew = now
        fresh = not self.is_leader.is_set()
        if generation != (self.generation or 0):
            LEADER_TRANSITIONS_TOTAL.inc()
        self.generation = generation
        # Arm BEFORE is_leader flips: the moment a waiter sees leadership
        # it may start mutating, and those writes must already carry the
        # new generation.
        self.cluster.fence.arm(self.identity, generation)
        if fresh:
            if self._campaign_began is not None:
                waited = max(0.0, now - self._campaign_began)
                LEADER_TAKEOVER_SECONDS.observe(waited)
                RECORDER.record(
                    "leader",
                    action="takeover",
                    holder=self.identity,
                    generation=generation,
                    waited_s=round(waited, 3),
                )
            else:
                RECORDER.record(
                    "leader",
                    action="acquire",
                    holder=self.identity,
                    generation=generation,
                )
            self._campaign_began = None
            self.is_leader.set()
            crashpoint("leader.after-acquire")
        return True

    def acquire(self, blocking: bool = True, poll_s: float = 1.0) -> bool:
        """Campaign until leadership (blocking) or one attempt; then keep
        renewing in the background."""
        while not self.try_acquire():
            if not blocking:
                return False
            if self._stop.wait(timeout=jittered_s(poll_s, rng=self._rng)):
                return False
        self._thread = threading.Thread(
            target=self._renew_loop, name="leader-renew", daemon=True
        )
        self._thread.start()
        return True

    def _renew_once(self) -> bool:
        """One renewal attempt; on failure (someone took our expired lease)
        drops leadership and fires on_lost.

        Fencing: if more than LEASE_SECONDS elapsed since our last successful
        renewal (a pause longer than the TTL — GC, suspend, store outage),
        the lease may have expired and a rival may have acquired it; re-CASing
        could steal it back mid-term, so leadership is declared lost WITHOUT
        attempting the CAS. The reference's leaderelection library likewise
        treats a missed renew deadline as lost leadership."""
        crashpoint("leader.before-renew")
        now = self.cluster.clock.now()
        if self._last_renew is None or now - self._last_renew > self.LEASE_SECONDS:
            self._lose()
            return False
        won = self.cluster.acquire_lease(
            self.LEASE_NAME, self.identity, self.LEASE_SECONDS
        )
        if won:
            self._last_renew = self.cluster.clock.now()
            return True
        self._lose()
        return False

    def _lose(self) -> None:
        """Leadership is gone: revoke the write fence FIRST — before on_lost
        and before is_leader clears — so no in-flight sweep can slip a write
        out between the loss and the manager stopping."""
        self.cluster.fence.revoke(self.identity)
        self.is_leader.clear()
        RECORDER.record(
            "leader",
            action="lose",
            holder=self.identity,
            generation=self.generation,
        )
        if self.on_lost is not None:
            self.on_lost()

    def _renew_loop(self) -> None:
        while not self._stop.wait(
            timeout=jittered_s(self.RENEW_SECONDS, rng=self._rng)
        ):
            if not self._renew_once():
                return

    def release(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self.is_leader.is_set():
            self.cluster.release_lease(self.LEASE_NAME, self.identity)
            self.cluster.fence.disarm(self.identity)
            self.is_leader.clear()


class LeaderLock:
    """Single-host leader election stand-in: an exclusive file lock.
    Kept for single-process deployments without a shared store; in-cluster
    runs use LeaderElector over the Lease analogue."""

    def __init__(self, path: str = "/tmp/karpenter-tpu-leader.lock"):
        self.path = path
        self._file = None

    def acquire(self, blocking: bool = True) -> bool:
        import fcntl

        self._file = open(self.path, "w")
        try:
            fcntl.flock(
                self._file,
                fcntl.LOCK_EX | (0 if blocking else fcntl.LOCK_NB),
            )
            return True
        except OSError:
            self._file.close()
            self._file = None
            return False

    def release(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


def make_solver(name: str, endpoint: str = ""):
    if name == "remote":
        # The solver-sidecar plugin boundary: solve RPCs to `endpoint`, host
        # greedy fallback + 30s blackout when it's unreachable.
        from karpenter_tpu.solver_service.client import RemoteSolver

        return RemoteSolver(endpoint)
    if name == "greedy":
        return GreedySolver()
    if name == "native":
        # Front-load the build (make -C native) here, at startup, rather than
        # inside the first reconcile; degrade loudly if no toolchain.
        from karpenter_tpu.ops import native

        if not native.available():
            klog.named("runtime").warning(
                "solver=native requested but the native library is "
                "unavailable (no C++ toolchain?); falling back to greedy"
            )
            return GreedySolver()
        return NativeSolver()
    if name == "ffd":
        return TPUSolver(mode="ffd")
    if name == "cost":
        return CostSolver()
    raise ValueError(f"unknown solver {name!r}")


class Manager:
    """Ref: pkg/controllers/manager.go RegisterControllers + cmd wiring."""

    def __init__(self, cluster: Cluster, cloud, options: Options):
        self.cluster = cluster
        self.cloud = cloud
        self.options = options
        self.log = klog.named("manager")
        self.solver = make_solver(options.solver, options.solver_endpoint)
        # The incremental encoder: subscribes to the store's verb-level
        # watch feed and keeps device-resident cluster tensors synced
        # O(churn); provisioning, consolidation, and interruption all solve
        # against it (docs/design/incremental-encode.md).
        from karpenter_tpu.models.cluster_state import DeviceClusterState

        self.cluster_state = DeviceClusterState(
            cluster,
            compaction_threshold=options.encode_compaction_threshold,
        )
        # The pod-latency SLO pipeline (utils/obs.py): the lifecycle tracker
        # rides the same verb-level watch feed as the incremental encoder —
        # O(churn) per sweep — and the evaluator takes its targets from the
        # --slo-pending-p99 / --slo-ttfl flags. Sharing the store's clock
        # keeps phase deltas honest under fake-clock harnesses.
        OBS.configure(
            clock=cluster.clock,
            slo_pending_p99=options.slo_pending_p99,
            slo_ttfl=options.slo_ttfl,
        )
        RECORDER.configure(clock=cluster.clock)
        OBS.attach(cluster)
        self.provisioning = ProvisioningController(
            cluster, cloud, self.solver, cluster_state=self.cluster_state,
            queue_max_pods=options.provision_queue_max_pods,
        )
        self.selection = SelectionController(cluster, self.provisioning)
        self.termination = TerminationController(cluster, cloud)
        # ONE voluntary-disruption ledger spans every voluntary actor —
        # consolidation, drift/expiration, and emptiness deletes all draw on
        # the same --disruption-budget, with per-reason caps nested inside.
        from karpenter_tpu.controllers.eligibility import DisruptionLedger
        from karpenter_tpu.controllers import eligibility as _eligibility

        self.disruption_ledger = DisruptionLedger(
            cluster,
            budget=options.disruption_budget,
            reason_caps={
                _eligibility.REASON_CONSOLIDATION: (
                    options.consolidation_max_disruption
                ),
                _eligibility.REASON_DRIFT: options.drift_max_disruption,
            },
        )
        self.node = NodeController(
            cluster,
            liveness_timeout=options.node_liveness_timeout,
            ledger=self.disruption_ledger,
        )
        self.counter = CounterController(cluster)
        self.metrics = MetricsController(cluster)
        self.podgc = PodGcController(cluster)
        self.instancegc = InstanceGcController(cluster, cloud)
        # Live market (karpenter_tpu/market): ONE PriceBook per controller
        # process, built BEFORE the controllers that feed or read it.
        from karpenter_tpu.market.pricebook import PriceBook, set_active_book

        self.price_book = PriceBook(
            clock=cluster.clock,
            reprice_threshold=options.reprice_threshold,
        )
        self.interruption = InterruptionController(
            cluster,
            cloud,
            self.provisioning,
            self.termination,
            escalate_fraction=options.interruption_escalate_fraction,
            cluster_state=self.cluster_state,
            price_book=self.price_book,
        )
        self.health = HealthController(
            cluster,
            cloud,
            self.provisioning,
            self.termination,
            unreachable_timeout=options.node_unreachable_timeout,
            drain_stuck_timeout=options.drain_stuck_timeout,
            cluster_state=self.cluster_state,
        )
        self.consolidation = ConsolidationController(
            cluster,
            cloud,
            self.provisioning,
            self.termination,
            max_disruption=options.consolidation_max_disruption,
            cooldown_seconds=options.consolidation_cooldown,
            cluster_state=self.cluster_state,
            ledger=self.disruption_ledger,
        )
        # Drift sweep: spec-hash + provider-side + expiration detection with
        # budgeted rolling replacement (docs/design/drift.md). Constructed
        # after consolidation so the two share the ledger and the same
        # provisioning/termination plumbing.
        from karpenter_tpu.controllers.drift import DriftController

        self.drift = DriftController(
            cluster,
            cloud,
            self.provisioning,
            self.termination,
            ledger=self.disruption_ledger,
            enabled=options.drift_enabled,
        )
        # The book (built above, before the controllers that feed it) folds
        # the provider's tick stream; set_active_book makes it the book the
        # solver-layer penalty/cache hooks read, attach_market makes the
        # provider's ADVERTISED spot prices track it, and the market sweep
        # requeues the cost controllers on debounced reprices. A restarted
        # Manager builds a fresh book and re-folds the provider's replayable
        # history from seq 0 — reconstructing the exact pre-crash state AND
        # generation (docs/design/market.md).
        set_active_book(self.price_book)
        cloud.attach_market(self.price_book)
        self.market = MarketController(
            cluster,
            cloud,
            self.price_book,
            debounce_seconds=options.reprice_debounce,
            # 0 = auto: the provider knows its own safe cadence (1s for the
            # in-memory fake, 15s on EC2 where a sweep is a paginated
            # DescribeSpotPriceHistory).
            sweep_seconds=options.market_poll_interval
            or getattr(cloud, "MARKET_POLL_DEFAULT_S", 1.0),
        )
        self.market.requeue = self._reprice_requeue
        self.ready = threading.Event()
        # Set once the solver's compile debt is paid (immediately for host
        # solvers). Gates /readyz AND the batch loop: a batch window that
        # closes during warmup holds its pods until the ladder is compiled,
        # so the first live solve runs at steady state — the reference boots
        # with zero compile debt (cmd/controller/main.go:61-99), and with
        # this, so does the default in-process deployment.
        self.warm = threading.Event()
        self._warming_can_serve = bool(
            getattr(self.solver, "host_fallback_available", lambda: False)()
        )
        # Pulsed by workers when a batch window FILLS (ProvisionerWorker
        # .batch_full); the batch loop waits on it so full windows
        # provision immediately.
        self._batch_full = threading.Event()
        self.provisioning.batch_full = self._batch_full
        self._stop = threading.Event()
        # Warm-standby mode (start_standby): the informer cache and the
        # DeviceClusterState sync run (both ride the store's watch feed,
        # wired at construction), the solver warmup ladder compiles, but no
        # reconcile loop starts and /readyz answers 503 "standby" until
        # start() activates on takeover.
        self.standby = threading.Event()
        self._warmup_kicked = False

        # Reconcile loops. The reference runs selection at
        # MaxConcurrentReconciles=10,000 (selection/controller.go:166) where
        # each reconcile parks on network I/O; here selection reconciles the
        # informer cache (CPU-bound under the GIL) and the loop is keyed +
        # collapse-deduped, with the batch overflow held by the worker —
        # so the envelope is picked from pod-storm data (bench.py
        # bench_pod_storm: 10k-pod drain ~1.8s at 8 threads and within
        # ~20% of that at 128 — extra threads buy nothing under the GIL,
        # they only pay wake/cache tax; see Options.selection_concurrency).
        self.loops = {
            "selection": ReconcileLoop(
                "selection",
                lambda key: self.selection.reconcile(*key),
                concurrency=options.selection_concurrency,
                # Selection reconciles the informer cache — pure CPU, ~100µs
                # each — so chunked dispatch amortizes queue/metric locking
                # across a storm without delaying anything slow.
                chunk=64,
            ),
            "provisioning": ReconcileLoop(
                "provisioning", self.provisioning.reconcile, concurrency=2
            ),
            "termination": ReconcileLoop(
                "termination", self.termination.reconcile, concurrency=4
            ),
            "node": ReconcileLoop("node", self.node.reconcile, concurrency=4),
            "counter": ReconcileLoop(
                "counter", lambda key: self.counter.reconcile(key), concurrency=1
            ),
            "metrics": ReconcileLoop(
                "metrics", self.metrics.reconcile, concurrency=1
            ),
            # Orphaned-pod reaper (kube-controller-manager podgc analogue):
            # a periodic self-requeuing sweep, like the metrics poll.
            "podgc": ReconcileLoop(
                "podgc", self.podgc.reconcile, concurrency=1
            ),
            # Leaked-capacity reaper: periodic self-requeuing sweep
            # reconciling provider instances (by ownership tag) against
            # Nodes — the money-side analogue of podgc.
            "instancegc": ReconcileLoop(
                "instancegc", self.instancegc.reconcile, concurrency=1
            ),
            # Interruption sweep: poll provider reclaim notices, drain
            # ahead of the deadline, replace before the pods land.
            "interruption": ReconcileLoop(
                "interruption", self.interruption.reconcile, concurrency=1
            ),
            # Node-health sweep: heartbeat staleness + NotReady detection
            # with flap hysteresis, escalating through the drain ladder.
            "health": ReconcileLoop(
                "health", self.health.reconcile, concurrency=1
            ),
            # Consolidation sweep: re-solve the live cluster for cost and
            # shed/replace capacity the workload no longer justifies.
            "consolidation": ReconcileLoop(
                "consolidation", self.consolidation.reconcile, concurrency=1
            ),
            # Drift sweep: compare live nodes against the current spec hash
            # and the provider's launch-template generation; roll drifted
            # capacity through the budgeted replacement path.
            "drift": ReconcileLoop(
                "drift", self.drift.reconcile, concurrency=1
            ),
            # Market sweep: poll the provider's price/ICE feed, fold ticks
            # into the PriceBook, requeue cost decisions on debounced
            # reprices — the dynamic analogue of the 5-minute drift requeue.
            "market": ReconcileLoop(
                "market", self.market.reconcile, concurrency=1
            ),
        }
        # Every loop worker binds the cluster's write fence so a deposed
        # leader's in-flight sweep aborts at its next crashpoint site
        # (cooperative abort; utils/fence.py).
        for loop in self.loops.values():
            loop.fence = cluster.fence

    # --- watch fan-out (ref: controller Register() watch wiring) ------------

    def _on_event(self, kind: str, obj) -> None:
        if kind == "pod":
            # Only provisionable pods route through selection: its reconcile
            # is a no-op for anything else, and a 10k-pod storm's bind wave
            # would otherwise re-enqueue every just-bound pod for a pointless
            # (GIL-bound) pass. The reference pays the same event with a
            # network-parked reconcile; here the event thread can filter on
            # the object it already holds.
            if obj.is_provisionable():
                self.loops["selection"].enqueue((obj.namespace, obj.name))
            if obj.node_name:
                # pod-to-node events re-list the node (ref: node/controller.go:118-150)
                self.loops["node"].enqueue(obj.node_name)
        elif kind == "node":
            self.loops["node"].enqueue(obj.name)
            self.loops["termination"].enqueue(obj.name)
            provisioner = obj.labels.get(wellknown.PROVISIONER_NAME_LABEL)
            if provisioner:
                self.loops["counter"].enqueue(provisioner)
        elif kind == "provisioner":
            self.loops["provisioning"].enqueue(obj.name)
            self.loops["counter"].enqueue(obj.name)
            self.loops["metrics"].enqueue(obj.name)

    def _on_delta(self, verb: str, kind: str, obj) -> None:
        """Terminal deletes prune the per-key error-backoff streaks
        (ReconcileLoop.forget): a pod/node that erred its way out of
        existence would otherwise leak one streak entry per churned object
        for the life of the process — invisible in 10-second smokes, a
        steady drip over a soak. Rides the store's verb-level feed; the
        plain watch (no verb) cannot see deletes as deletes."""
        if verb != "delete":
            return
        if kind == "pod":
            self.loops["selection"].forget((obj.namespace, obj.name))
        elif kind == "node":
            self.loops["node"].forget(obj.name)
            self.loops["termination"].forget(obj.name)
        elif kind == "provisioner":
            for name in ("provisioning", "counter", "metrics"):
                self.loops[name].forget(obj.name)

    # --- batch loop ---------------------------------------------------------

    def _batch_loop(self) -> None:
        # The batch loop launches capacity, so its thread binds the fence
        # too: a provision pass caught mid-flight by a leadership loss
        # aborts at its next crashpoint site (utils/fence.py).
        bind_thread(self.cluster.fence)
        while not self._stop.is_set():
            # Wake on the next poll tick OR the instant a window fills —
            # a storm's full batches provision without paying up to a poll
            # interval of latency each (idle-closed windows still close on
            # the tick, since their edge is a clock passing, not an event).
            self._batch_full.wait(timeout=BATCH_IDLE_SECONDS / 5)
            self._batch_full.clear()
            if self._stop.is_set():
                return
            if not self.warm.is_set() and not self._warming_can_serve:
                # No host fallback: batches accumulate until the ladder is
                # compiled, so no live batch ever pays the jit stall. With a
                # fallback, provisioning continues — solves route host-side
                # via the warming preference (models/solver.py).
                continue
            for worker in list(self.provisioning.workers.values()):
                if worker.batch_ready():
                    try:
                        worker.provision()
                    except Exception as error:  # noqa: BLE001
                        self.log.exception("provisioning pass failed")
                        # The batch loop's own degradation signal: a failed
                        # provision pass (API storm mid-bind, launch fault)
                        # leaves the batch queued and the loop alive.
                        SWEEP_FAILURES_TOTAL.inc("batch", type(error).__name__)

    def _requeue_loop(self) -> None:
        """5-minute provisioner refresh to pick up instance-type drift
        (ref: provisioning/controller.go:80)."""
        while not self._stop.wait(timeout=ProvisioningController.REQUEUE_SECONDS):
            for provisioner in self.cluster.list_provisioners():
                self.loops["provisioning"].enqueue(provisioner.name)

    def _reprice_requeue(self) -> None:
        """The market sweep's requeue hook: a debounced reprice pulls every
        provisioner refresh AND a consolidation sweep forward NOW (enqueue
        at delay 0 supersedes the poll interval) — the dynamic analogue of
        _requeue_loop's 5-minute drift timer."""
        for provisioner in self.cluster.list_provisioners():
            self.loops["provisioning"].enqueue(provisioner.name)
        self.loops["consolidation"].enqueue("sweep")
        # A reprice can flip a spot pool's sustained-ICE drift verdict, so
        # the drift sweep is pulled forward with the other cost decisions.
        self.loops["drift"].enqueue("sweep")

    # --- lifecycle ----------------------------------------------------------

    def start_standby(self) -> None:
        """Warm standby: everything read-only a takeover would otherwise pay
        for. The informer cache and DeviceClusterState sync already ride the
        store's watch feed (wired at construction), so this only kicks the
        solver warmup ladder — the XLA compile debt — leaving /readyz at 503
        "standby" and every reconcile loop parked until start()."""
        self.standby.set()
        self._kick_warmup()

    def start(self) -> None:
        self.standby.clear()
        self.cluster.watch(self._on_event)
        self.cluster.watch_deltas(self._on_delta)
        for loop in self.loops.values():
            loop.start()
        # Standalone eviction pump (ref: termination/eviction.go:45-57): the
        # queue drains even when no termination reconcile is in flight.
        self.termination.evictions.start()
        threading.Thread(
            target=self._batch_loop, name="provision-batcher", daemon=True
        ).start()
        threading.Thread(
            target=self._requeue_loop, name="backoff-requeue", daemon=True
        ).start()
        # Seed existing state.
        for provisioner in self.cluster.list_provisioners():
            self.loops["provisioning"].enqueue(provisioner.name)
            self.loops["metrics"].enqueue(provisioner.name)
        for pod in self.cluster.list_pods():
            self.loops["selection"].enqueue((pod.namespace, pod.name))
        for node in self.cluster.list_nodes():
            self.loops["node"].enqueue(node.name)
        self.loops["podgc"].enqueue("sweep")
        self.loops["instancegc"].enqueue("sweep")
        self.loops["interruption"].enqueue("sweep")
        self.loops["health"].enqueue("sweep")
        self.loops["consolidation"].enqueue("sweep")
        self.loops["drift"].enqueue("sweep")
        self.loops["market"].enqueue("sweep")
        self._kick_warmup()
        if self.warm.is_set() and not self._stop.is_set():
            # Activating from a standby whose warmup already finished: the
            # warmup thread set `warm` while `standby` held readiness back.
            self.ready.set()

    def _kick_warmup(self) -> None:
        """Start the solver warmup exactly once per Manager — standby kicks
        it early, activation reuses the result (bounded time-to-first-launch:
        a takeover never pays XLA compile on a live batch)."""
        if self._warmup_kicked:
            return
        self._warmup_kicked = True
        if getattr(self.solver, "needs_device_warmup", False):
            from karpenter_tpu.utils import backend_health

            # One verdict before any in-process device touch: a wedged
            # accelerator at boot must produce an explicit degraded mode
            # (pinned CPU backend, host-hybrid routing, /readyz up) — not a
            # warmup thread hanging in C behind a 503 forever.
            boot = backend_health.ensure_backend()
            if boot.state == backend_health.DEGRADED:
                self.log.warning(
                    "accelerator backend degraded at boot (%s): skipping "
                    "device warmup; solves route to the native host hybrid "
                    "(backend_probe_result=0 in /metrics)",
                    boot.reason,
                )
                self.warm.set()
                self._assert_ready()
            else:
                threading.Thread(
                    target=self._warmup, name="solver-warmup", daemon=True
                ).start()
        else:
            self.warm.set()
            self._assert_ready()

    def _assert_ready(self) -> None:
        """warm -> ready, unless stopped (a deposed leader's loops are all
        down — /readyz must not flip back to 200) or still a standby (ready
        means 'routable for work'; a standby is warm but not active)."""
        if not self._stop.is_set() and not self.standby.is_set():
            self.ready.set()

    def _warmup(self) -> None:
        """In-process analogue of the sidecar's boot warmup
        (solver_service/server.py): reconcile loops serve immediately;
        /readyz and the batch loop wait for the ladder."""
        try:
            from karpenter_tpu.models.warmup import warmup_ladder

            warmup_ladder()
        except Exception:  # noqa: BLE001 — warmup must never wedge boot
            self.log.exception("solver warmup failed; serving anyway")
        self.warm.set()
        self._assert_ready()

    def reload_options(self, changed: dict) -> None:
        """Apply a re-parsed reloadable Options subset (options.RELOADABLE)
        live — the SIGHUP / POST /debug/loglevel path. `changed` maps field
        name to new value (options.apply_reload's return)."""
        if not changed:
            return
        if "log_level" in changed:
            klog.set_level(changed["log_level"])
        if "slo_pending_p99" in changed or "slo_ttfl" in changed:
            OBS.configure(
                clock=self.cluster.clock,
                slo_pending_p99=self.options.slo_pending_p99,
                slo_ttfl=self.options.slo_ttfl,
            )
        self.log.info("reloaded options: %s", sorted(changed))

    def stop(self) -> None:
        self._stop.set()
        self._batch_full.set()  # unblock the batch loop promptly
        for loop in self.loops.values():
            loop.stop()
        self.termination.evictions.stop()
        self.ready.clear()

    def healthy(self) -> bool:
        """False once stopped — flips /healthz to 503 (a deposed leader must
        fail its liveness probe, not idle at 200)."""
        return not self._stop.is_set()


class _HTTPHandler(http.server.BaseHTTPRequestHandler):
    manager: Optional[Manager] = None

    def do_GET(self):  # noqa: N802 — http.server API
        if self.path == "/metrics":
            body = REGISTRY.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
        elif self.path == "/debug/flightrecorder":
            # The black box, on demand: a consistent snapshot of the
            # decision/fault ring with seq/dropped metadata so the reader
            # can prove it gap-free (docs/design/observability.md).
            body = RECORDER.dump_json().encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path == "/debug/slo":
            body = json.dumps(OBS.slo_snapshot(), default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path == "/debug/stacks":
            # Instantaneous stacks + a short StackProf sample: "what is the
            # process wedged on / burning on" without attaching a debugger.
            body = json.dumps(stacks_snapshot(), default=str).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path == "/healthz":
            # Unhealthy once the manager stops (e.g. deposed leader) so the
            # liveness probe restarts the pod instead of letting a stopped
            # replica idle at 200.
            healthy = self.manager is None or self.manager.healthy()
            body = b"ok" if healthy else b"stopped"
            self.send_response(200 if healthy else 503)
            self.send_header("Content-Type", "text/plain")
        elif self.path == "/readyz":
            ready = self.manager is not None and self.manager.ready.is_set()
            if ready:
                body, status = b"ok", 200
            elif self.manager is not None and self.manager.standby.is_set():
                # A campaigning standby is healthy-but-not-routable: the
                # distinct body lets probes (and operators) tell a warm
                # standby from a replica that is genuinely not up yet.
                body, status = b"standby", 503
            else:
                body, status = b"not ready", 503
            self.send_response(status)
            self.send_header("Content-Type", "text/plain")
        elif self.path == "/debug/loglevel":
            body = json.dumps({"level": klog.get_level()}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        else:
            body = b"not found"
            self.send_response(404)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802 — http.server API
        """POST /debug/loglevel with `debug` or `{"level": "debug"}` flips
        the root logger live — the remote half of the SIGHUP reload path
        (cmd/controller.py); both route through Manager.reload_options."""
        if self.path != "/debug/loglevel":
            body = b"not found"
            self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length).decode("utf-8", "replace").strip()
        level = raw
        if raw.startswith("{"):
            try:
                level = str(json.loads(raw).get("level", ""))
            except ValueError:
                level = ""
        level = level.strip().strip('"').lower()
        if level not in ("debug", "info", "warning", "error"):
            body = json.dumps({"error": f"unknown level {level!r}"}).encode()
            self.send_response(400)
        else:
            if self.manager is not None:
                self.manager.options.log_level = level
                self.manager.reload_options({"log_level": level})
            else:
                klog.set_level(level)
            body = json.dumps({"level": level}).encode()
            self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    do_PUT = do_POST  # noqa: N815 — same semantics either verb

    def log_message(self, *args):  # silence per-request logging
        pass


def serve_http(
    manager: Manager, port: int, address: str = ""
) -> http.server.ThreadingHTTPServer:
    # Default bind is all interfaces: the scrape/probe traffic this serves
    # arrives over the pod IP in a real deployment.
    handler = type("Handler", (_HTTPHandler,), {"manager": manager})
    server = http.server.ThreadingHTTPServer((address, port), handler)
    threading.Thread(
        target=server.serve_forever, name="http-serve", daemon=True
    ).start()
    return server
