"""A seeded, replayable spot-market tick stream.

The reference requeues provisioners every 5 minutes purely to pick up
instance-type/pricing drift (SURVEY.md §2.2) — the market is an input that
*changes*. This module is the fake/simulated source of that change: a
regime-switching random walk over each pool's spot discount and capacity
depth, plus ICE (insufficient-capacity) open/close churn, emitted as a
strictly-ordered tick sequence.

Determinism contract (the crash battletest leans on every clause):

- The walk is driven by ONE ``random.Random(seed)``; the tick sequence is a
  pure function of (pools, seed, tunables, number of steps taken). Two feeds
  built alike and advanced to the same step count emit byte-identical ticks
  (``MarketTick.encode``).
- Every emitted tick is retained in order; ``ticks_after(seq)`` replays any
  suffix. A restarted controller re-folds from seq 0 and reconstructs the
  exact PriceBook state and generation the dead one had — no ack protocol,
  no controller-side durable cursor (the feed IS the durable history, the
  way DescribeSpotPriceHistory is on EC2).
- Scripted shoves (``force_spike``, ``force_ice``) take effect at the next
  step and are recorded as ordinary ticks, so a replay that includes them is
  still just ``ticks_after(0)``.

Steps are paced by the provider's clock: ``advance(now)`` emits the ticks
for every elapsed ``tick_interval_s`` since construction. The fake provider
calls it at each ``poll_market_events``; tests call it directly.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

Pool = Tuple[str, str]  # (instance_type_name, zone)

TICK_PRICE = "price"
TICK_ICE_CLOSE = "ice-close"
TICK_ICE_OPEN = "ice-open"

# Market regimes: calm drifts, volatile swings, spike ratchets the discount
# up (spot price toward on-demand — the market losing depth).
REGIME_CALM = 0
REGIME_VOLATILE = 1
REGIME_SPIKE = 2

# Per-step regime transition probabilities (row = current regime). Spikes
# are rare and short; volatility is the common excited state.
_TRANSITIONS = {
    REGIME_CALM: ((REGIME_VOLATILE, 0.05), (REGIME_SPIKE, 0.01)),
    REGIME_VOLATILE: ((REGIME_CALM, 0.15), (REGIME_SPIKE, 0.03)),
    REGIME_SPIKE: ((REGIME_VOLATILE, 0.35),),
}
# Multiplicative walk sigma per regime (log-ish steps, clamped).
_SIGMA = {REGIME_CALM: 0.01, REGIME_VOLATILE: 0.05, REGIME_SPIKE: 0.0}
# Spike regime: discount ratchets up by this factor per step while depth
# decays — the "pool is being bought out from under you" shape the forecast
# exists to catch BEFORE the interruptions land.
_SPIKE_DISCOUNT_STEP = 1.25
_SPIKE_DEPTH_STEP = 0.6

MIN_DISCOUNT = 0.2
MAX_DISCOUNT = 0.98
MIN_DEPTH = 0.05
MAX_DEPTH = 4.0


@dataclass(frozen=True)
class MarketTick:
    """One market event. ``seq`` is the feed-global strict order; ``at`` is
    the feed-clock timestamp the event happened at. ``price`` kinds carry
    the pool's new discount (spot/on-demand ratio) and depth; ICE kinds
    toggle the pool's spot availability."""

    seq: int
    kind: str  # TICK_PRICE | TICK_ICE_CLOSE | TICK_ICE_OPEN
    instance_type: str
    zone: str
    discount: float = 1.0
    depth: float = 1.0
    at: float = 0.0

    @property
    def pool(self) -> Pool:
        return (self.instance_type, self.zone)

    def encode(self) -> str:
        """Canonical wire form — the determinism tests compare these, so
        two 'identical' tick sequences must agree to the last bit."""
        return "|".join(
            (
                str(self.seq),
                self.kind,
                self.instance_type,
                self.zone,
                repr(self.discount),
                repr(self.depth),
                repr(self.at),
            )
        )


class MarketFeed:
    """Regime-switching walk over a fixed pool set. Thread-safe: the
    provider polls from sweep threads while tests shove spikes in."""

    def __init__(
        self,
        pools: Sequence[Pool],
        seed: int = 0,
        tick_interval_s: float = 1.0,
        start_at: float = 0.0,
        initial_discount: float = 0.55,
        ice_close_rate: float = 0.0,
        ice_reopen_rate: float = 0.25,
    ):
        self.pools = [tuple(pool) for pool in pools]
        self.tick_interval_s = float(tick_interval_s)
        self.ice_close_rate = float(ice_close_rate)
        self.ice_reopen_rate = float(ice_reopen_rate)
        self._rng = random.Random(seed)  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()
        self._anchor = float(start_at)  # vet: guarded-by(self._lock)
        self._steps = 0  # vet: guarded-by(self._lock)
        self._seq = 0  # vet: guarded-by(self._lock)
        self._history: List[MarketTick] = []  # vet: guarded-by(self._lock)
        self._discount: Dict[Pool, float] = {}  # vet: guarded-by(self._lock)
        self._depth: Dict[Pool, float] = {}  # vet: guarded-by(self._lock)
        self._regime: Dict[Pool, int] = {}  # vet: guarded-by(self._lock)
        self._closed: Dict[Pool, bool] = {}  # vet: guarded-by(self._lock)
        self._forced_spike: Dict[Pool, float] = {}  # vet: guarded-by(self._lock)
        self._forced_ice: Dict[Pool, str] = {}  # vet: guarded-by(self._lock)
        with self._lock:
            for pool in self.pools:
                # Seeded initial state, then one snapshot tick per pool so a
                # fold from seq 0 sees the whole market before any step.
                self._discount[pool] = _clamp(
                    initial_discount * (0.9 + 0.2 * self._rng.random()),
                    MIN_DISCOUNT,
                    MAX_DISCOUNT,
                )
                self._depth[pool] = _clamp(
                    0.5 + self._rng.random(), MIN_DEPTH, MAX_DEPTH
                )
                self._regime[pool] = REGIME_CALM
                self._closed[pool] = False
                self._emit_price_locked(pool, self._anchor)

    def rebase(self, start_at: float) -> None:
        """Re-anchor an UN-STEPPED feed's clock — the attach-time guard
        against the epoch-anchor footgun: a feed built with the default
        start_at=0.0 and polled against a provider clock sitting at, say,
        1e6 would owe a million steps at the first poll. The provider
        calls this at attach; once any step has run it is a no-op (the
        walk's history is immutable). The initial per-pool snapshot ticks
        restamp to the new anchor so feed staleness starts at zero."""
        from dataclasses import replace

        with self._lock:
            if self._steps:
                return
            self._anchor = float(start_at)
            self._history = [
                replace(tick, at=self._anchor) for tick in self._history
            ]

    # --- scripted shoves (take effect at the next step, as ticks) ----------

    def force_spike(self, pools: Iterable[Pool], factor: float) -> None:
        """Script a price spike: at the next step each pool's discount jumps
        by ``factor`` (clamped) and its regime goes SPIKE. Recorded as
        ordinary price ticks, so replay determinism is untouched."""
        with self._lock:
            for pool in pools:
                self._forced_spike[tuple(pool)] = float(factor)

    def force_ice(self, pools: Iterable[Pool], close: bool = True) -> None:
        """Script ICE churn: close (or reopen) pools at the next step."""
        kind = TICK_ICE_CLOSE if close else TICK_ICE_OPEN
        with self._lock:
            for pool in pools:
                self._forced_ice[tuple(pool)] = kind

    # --- stream -------------------------------------------------------------

    def advance(self, now: float) -> int:
        """Emit ticks for every tick_interval_s elapsed since construction;
        returns how many steps ran."""
        with self._lock:
            due = int(max(0.0, now - self._anchor) / self.tick_interval_s)
            ran = 0
            while self._steps < due:
                self._steps += 1
                self._step_locked(
                    self._anchor + self._steps * self.tick_interval_s
                )
                ran += 1
            return ran

    def ticks_after(self, seq: int) -> List[MarketTick]:
        with self._lock:
            if seq <= 0:
                return list(self._history)
            # seqs are dense and 1-based: history[k] has seq k+1.
            return list(self._history[seq:])

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def encode_history(self) -> List[str]:
        with self._lock:
            return [tick.encode() for tick in self._history]

    # --- the walk -----------------------------------------------------------

    def _step_locked(self, at: float) -> None:
        for pool in self.pools:
            self._step_pool_locked(pool, at)

    def _step_pool_locked(self, pool: Pool, at: float) -> None:
        forced_ice = self._forced_ice.pop(pool, None)
        if forced_ice is not None:
            self._emit_ice_locked(pool, forced_ice, at)
        elif self._closed[pool]:
            if self._rng.random() < self.ice_reopen_rate:
                self._emit_ice_locked(pool, TICK_ICE_OPEN, at)
        elif self.ice_close_rate and self._rng.random() < self.ice_close_rate:
            self._emit_ice_locked(pool, TICK_ICE_CLOSE, at)

        forced = self._forced_spike.pop(pool, None)
        if forced is not None:
            self._regime[pool] = REGIME_SPIKE
            self._discount[pool] = _clamp(
                self._discount[pool] * forced, MIN_DISCOUNT, MAX_DISCOUNT
            )
            self._depth[pool] = _clamp(
                self._depth[pool] * _SPIKE_DEPTH_STEP, MIN_DEPTH, MAX_DEPTH
            )
            self._emit_price_locked(pool, at)
            return
        regime = self._next_regime_locked(self._regime[pool])
        self._regime[pool] = regime
        if regime == REGIME_SPIKE:
            self._discount[pool] = _clamp(
                self._discount[pool] * _SPIKE_DISCOUNT_STEP,
                MIN_DISCOUNT,
                MAX_DISCOUNT,
            )
            self._depth[pool] = _clamp(
                self._depth[pool] * _SPIKE_DEPTH_STEP, MIN_DEPTH, MAX_DEPTH
            )
        else:
            sigma = _SIGMA[regime]
            self._discount[pool] = _clamp(
                self._discount[pool]
                * (1.0 + sigma * (2.0 * self._rng.random() - 1.0)),
                MIN_DISCOUNT,
                MAX_DISCOUNT,
            )
            # Depth moves loosely AGAINST price (a draining pool gets
            # pricier), plus its own noise.
            self._depth[pool] = _clamp(
                self._depth[pool]
                * (1.0 + 2.0 * sigma * (2.0 * self._rng.random() - 1.0)),
                MIN_DEPTH,
                MAX_DEPTH,
            )
        self._emit_price_locked(pool, at)

    def _next_regime_locked(self, regime: int) -> int:
        roll = self._rng.random()
        acc = 0.0
        for target, probability in _TRANSITIONS[regime]:
            acc += probability
            if roll < acc:
                return target
        return regime

    # --- emit ---------------------------------------------------------------

    def _emit_price_locked(self, pool: Pool, at: float) -> None:
        self._seq += 1
        self._history.append(
            MarketTick(
                seq=self._seq,
                kind=TICK_PRICE,
                instance_type=pool[0],
                zone=pool[1],
                discount=self._discount[pool],
                depth=self._depth[pool],
                at=at,
            )
        )

    def _emit_ice_locked(self, pool: Pool, kind: str, at: float) -> None:
        self._closed[pool] = kind == TICK_ICE_CLOSE
        self._seq += 1
        self._history.append(
            MarketTick(
                seq=self._seq,
                kind=kind,
                instance_type=pool[0],
                zone=pool[1],
                discount=self._discount[pool],
                depth=self._depth[pool],
                at=at,
            )
        )


def _clamp(value: float, lo: float, hi: float) -> float:
    return max(lo, min(hi, value))


def catalog_pools(
    instance_types, capacity_type: str = "spot"
) -> List[Pool]:
    """Every (type, zone) pool a catalog offers at ``capacity_type`` — the
    usual feed universe for a provider's catalog."""
    pools: List[Pool] = []
    seen = set()
    for it in instance_types:
        for offering in it.offerings:
            if offering.capacity_type != capacity_type:
                continue
            pool = (it.name, offering.zone)
            if pool not in seen:
                seen.add(pool)
                pools.append(pool)
    return pools


__all__ = [
    "MarketFeed",
    "MarketTick",
    "TICK_PRICE",
    "TICK_ICE_CLOSE",
    "TICK_ICE_OPEN",
    "catalog_pools",
]
