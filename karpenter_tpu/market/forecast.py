"""Interruption-risk forecast, lowered as a per-[T] price penalty column.

The PriceBook tracks per-pool hazard (depth-decline trend + recently
observed interruptions — ``PriceBook.pool_risk``); this module turns it into
the [T] float32 penalty column the packing stack consumes:

    penalty[t] = prices[t] * risk[t] * RISK_PRICE_WEIGHT
    effective_prices = float32(prices + penalty)

The column is computed HOST-SIDE (numpy, float32) and added to the price
vector *before* dispatch, so the fused device kernel and every numpy host
mirror (greedy/native/mix) consume the same bits — forecast-aware packing
cannot open a kernel/host parity gap by construction. ``penalize_prices_jnp``
is the jax mirror of the same arithmetic; tests assert it bit-identical to
the numpy path (the acceptance gate's parity clause).

Applied in two places:

- ``ops.encode.build_fleet`` penalizes the [T] cheapest-offering prices
  (spot fleets only) — provisioning solves AND consolidation's replacement
  scoring (``_replacement_fleet`` routes through build_fleet) both pack away
  from pools trending toward interruption *before* they interrupt.
- ``models.solver._pool_price_matrix`` penalizes the [T, Z] pool ranking so
  pinned launch rows (CreateFleet overrides) avoid risky pools too.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from karpenter_tpu.market.pricebook import PriceBook

# How much of a pool's price one unit of risk adds: 1.0 means a pool at
# quantized risk 0.5 competes as if it cost 1.5x its advertised price — the
# implied cost of the restart churn an interruption causes.
RISK_PRICE_WEIGHT = 1.0


def type_risks(
    type_names: Sequence[str],
    zones_per_type: Sequence[Sequence[str]],
    book: PriceBook,
) -> np.ndarray:
    """[T] float32 hazard per type: the worst (max) risk across the type's
    allowed zones — conservative, so one draining zone is enough to steer
    packing toward a calmer type. One risk_snapshot() (single lock/clock
    round trip) serves the whole T x Z loop."""
    snapshot = book.risk_snapshot()
    risks = np.zeros(len(type_names), dtype=np.float32)
    for index, (name, zones) in enumerate(zip(type_names, zones_per_type)):
        worst = 0.0
        for zone in zones:
            worst = max(worst, snapshot.get((name, zone), 0.0))
        risks[index] = worst
    return risks


def penalty_column(prices: np.ndarray, risks: np.ndarray) -> np.ndarray:
    """[T] float32 penalty — the column lowered into the kernel dispatch."""
    return (
        prices.astype(np.float32)
        * risks.astype(np.float32)
        * np.float32(RISK_PRICE_WEIGHT)
    )


def penalize_prices(prices: np.ndarray, risks: np.ndarray) -> np.ndarray:
    """float32 effective prices = prices + penalty (the numpy path — what
    build_fleet publishes and every solver consumes)."""
    return (
        prices.astype(np.float32) + penalty_column(prices, risks)
    ).astype(np.float32)


def penalize_prices_jnp(prices, risks):
    """The jax mirror of penalize_prices — same dtypes, same operation
    order. Tests assert np.asarray(penalize_prices_jnp(...)) is
    BIT-IDENTICAL to penalize_prices(...); the production path feeds the
    numpy column to both kernel and mirror, so this is a tripwire for the
    arithmetic ever diverging, not a second implementation to maintain."""
    import jax.numpy as jnp

    prices32 = jnp.asarray(prices, dtype=jnp.float32)
    risks32 = jnp.asarray(risks, dtype=jnp.float32)
    return (
        prices32 + prices32 * risks32 * jnp.float32(RISK_PRICE_WEIGHT)
    ).astype(jnp.float32)


def risk_matrix(
    type_names: Sequence[str],
    zones: Sequence[str],
    book: PriceBook,
) -> np.ndarray:
    """[T, Z] float64 per-pool risk for the launch pool-ranking matrix —
    one risk_snapshot() serves the whole grid (see type_risks)."""
    snapshot = book.risk_snapshot()
    out = np.zeros((len(type_names), len(zones)), dtype=np.float64)
    for ti, name in enumerate(type_names):
        for zi, zone in enumerate(zones):
            out[ti, zi] = snapshot.get((name, zone), 0.0)
    return out


def fleet_zone_lists(kept, allowed_zones) -> List[List[str]]:
    """Per-kept-type allowed zone lists for type_risks — shared by the
    build_fleet hook so both fast and slow kept paths derive identically."""
    return [
        sorted(z for z in item[0].zones() if allowed_zones.contains(z))
        for item in kept
    ]


__all__ = [
    "RISK_PRICE_WEIGHT",
    "fleet_zone_lists",
    "penalize_prices",
    "penalize_prices_jnp",
    "penalty_column",
    "risk_matrix",
    "type_risks",
]
