"""Live market dynamics: the spot market as a STREAMED input.

`cloudprovider/market.py` models what the market IS (per-pool discount/depth
state and the fleet-allocation semantics that price a plan against it); this
package models how that state MOVES and how the control plane reacts:

- ``feed``      — a seeded, replayable tick stream (regime-switching walk
                  over discount/depth per pool, plus ICE open/close churn)
                  delivered through ``CloudProvider.poll_market_events``.
- ``pricebook`` — the controller-side fold of that stream: a generation-
                  tagged view of the current market that every cost decision
                  (provisioning, consolidation, launch pool ranking) reads,
                  plus the per-pool interruption-hazard state the forecast
                  derives from depth trend + observed interruptions.
- ``forecast``  — the interruption-risk estimator lowered as a per-[T]
                  penalty column into the fused kernel dispatch and the
                  consolidation scoring (bit-identical numpy mirror).

The sweep that drives it lives in ``controllers/market.py``; see
docs/design/market.md for the feed model, the generation/invalidation
protocol, and the storm composition (`make market-smoke`).

Everything here is jax-free (numpy only): the penalty column is computed
host-side and ADDED to the [T] price vector both the device kernel and the
numpy host mirrors consume, so forecast-aware packing cannot introduce a
kernel/host parity gap by construction.
"""

from __future__ import annotations

_EXPORTS = {
    "MarketFeed": "karpenter_tpu.market.feed",
    "MarketTick": "karpenter_tpu.market.feed",
    "PriceBook": "karpenter_tpu.market.pricebook",
    "Reprice": "karpenter_tpu.market.pricebook",
    "active_book": "karpenter_tpu.market.pricebook",
    "set_active_book": "karpenter_tpu.market.pricebook",
}


def __getattr__(name):  # PEP 562 — submodules import lazily
    module_name = _EXPORTS.get(name)
    if module_name is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module_name), name)
