"""The controller-side fold of the market tick stream.

A ``PriceBook`` is a generation-tagged view of the current spot market: the
latest per-pool discount/depth, which pools are ICE-closed, and the hazard
state the interruption forecast reads (depth trend + recently observed
interruptions). It is rebuilt from scratch on every controller restart by
replaying the provider's tick history from seq 0 — applying is a pure,
idempotent fold (a tick at or below ``last_seq`` is a no-op), so a restart
reconstructs the exact pre-crash state AND generation.

Generation protocol (docs/design/market.md):

- ``generation`` bumps when a pool's discount drifts at least
  ``reprice_threshold`` (relative) away from its anchor — the discount at
  the last bump — or on any ICE open/close. Many sub-threshold ticks that
  cumulatively cross the threshold DO reprice; a storm of tiny jitters does
  not. Consumers key caches on the generation:
  * provisioning stamps it into the compiled-envelope cache key
    (``stamp_epoch``), so a reprice invalidates PR 10's envelopes;
  * ``DeviceClusterState.encode_fleet`` keys its fleet cache on
    ``active_fingerprint()``, and the rebuilt fleet's changed price bytes
    miss PR 6's content-keyed device-resident cache — the offering arrays
    re-upload exactly when the market moved.
- ``risk_generation`` bumps when the forecast-relevant state changes
  materially (an observed interruption, or a pool's QUANTIZED risk score
  moving) — quantization keeps ordinary depth noise from churning the fleet
  cache every tick.

One book is process-global-active at a time (``set_active_book``): the
penalty hooks in ``ops.encode.build_fleet`` / ``models.solver`` read it
lazily so the whole solver stack — device kernels and numpy mirrors alike —
prices against the same market without threading a handle through every
layer. Tests reset it via the autouse fixture in tests/conftest.py.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Set, Tuple

from karpenter_tpu.cloudprovider.market import SpotMarket
from karpenter_tpu.market.feed import (
    TICK_ICE_CLOSE,
    TICK_ICE_OPEN,
    TICK_PRICE,
    MarketTick,
    Pool,
)
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK

DEFAULT_REPRICE_THRESHOLD = 0.1  # relative discount drift that forces a re-solve

REASON_PRICE = "price-delta"
REASON_ICE = "ice"

# Hazard model: risk = 1 - exp(-(decline + interruptions)) with the depth
# decline trend EWMA'd per pool and observed interruptions decaying on a
# half-life. Quantized to RISK_QUANTUM steps for cache stability.
TREND_EWMA = 0.3
TREND_WEIGHT = 6.0
INTERRUPTION_WEIGHT = 0.8
INTERRUPTION_HALF_LIFE_S = 300.0
RISK_QUANTUM = 1.0 / 32.0


@dataclass(frozen=True)
class Reprice:
    """One generation bump, as the controller's flight record sees it."""

    pool: Pool
    reason: str  # REASON_PRICE | REASON_ICE
    old_discount: float
    new_discount: float
    generation: int


class PriceBook:
    def __init__(
        self,
        clock: Optional[Clock] = None,
        reprice_threshold: float = DEFAULT_REPRICE_THRESHOLD,
    ):
        self.clock = clock or SYSTEM_CLOCK
        self.reprice_threshold = float(reprice_threshold)
        self._lock = threading.Lock()
        self._generation = 0  # vet: guarded-by(self._lock)
        self._risk_generation = 0  # vet: guarded-by(self._lock)
        self._last_seq = 0  # vet: guarded-by(self._lock)
        self._discount: Dict[Pool, float] = {}  # vet: guarded-by(self._lock)
        self._depth: Dict[Pool, float] = {}  # vet: guarded-by(self._lock)
        self._anchor: Dict[Pool, float] = {}  # vet: guarded-by(self._lock)
        self._closed: Set[Pool] = set()  # vet: guarded-by(self._lock)
        # pool -> feed time (tick.at) the CURRENT closure began. Stamped from
        # the tick, not the wall clock, so a restart's replay reconstructs
        # the identical closure age — the drift sweep's sustained-ICE window
        # (closed_since) stays deterministic across crashes.
        self._closed_at: Dict[Pool, float] = {}  # vet: guarded-by(self._lock)
        self._trend: Dict[Pool, float] = {}  # vet: guarded-by(self._lock)
        self._risk_q: Dict[Pool, float] = {}  # vet: guarded-by(self._lock)
        # pool -> (decayed count, clock stamp of last decay)
        self._interruptions: Dict[Pool, Tuple[float, float]] = {}  # vet: guarded-by(self._lock)
        self._last_tick_at: Optional[float] = None  # vet: guarded-by(self._lock)

    # --- fold ---------------------------------------------------------------

    def apply(self, tick: MarketTick) -> Optional[Reprice]:
        """Fold one tick; returns the Reprice when the generation bumped.
        Idempotent on seq: replays (at-least-once delivery, restart re-folds)
        are no-ops past the high-water mark."""
        with self._lock:
            if tick.seq <= self._last_seq:
                return None
            self._last_seq = tick.seq
            self._last_tick_at = tick.at
            if tick.kind in (TICK_ICE_CLOSE, TICK_ICE_OPEN):
                return self._apply_ice_locked(tick)
            if tick.kind != TICK_PRICE:
                return None
            return self._apply_price_locked(tick)

    def _apply_ice_locked(self, tick: MarketTick) -> Reprice:
        if tick.kind == TICK_ICE_CLOSE:
            self._closed.add(tick.pool)
            # setdefault: a repeated close while already closed must not
            # reset the closure age (the sustained-ICE drift window would
            # never elapse under a re-asserting feed).
            self._closed_at.setdefault(tick.pool, tick.at)
        else:
            self._closed.discard(tick.pool)
            self._closed_at.pop(tick.pool, None)
        self._generation += 1
        discount = self._discount.get(tick.pool, tick.discount)
        return Reprice(
            pool=tick.pool,
            reason=REASON_ICE,
            old_discount=discount,
            new_discount=discount,
            generation=self._generation,
        )

    def _apply_price_locked(self, tick: MarketTick) -> Optional[Reprice]:
        pool = tick.pool
        previous_depth = self._depth.get(pool)
        self._discount[pool] = tick.discount
        self._depth[pool] = tick.depth
        if previous_depth is not None and previous_depth > 0:
            delta = (tick.depth - previous_depth) / previous_depth
            trend = (1.0 - TREND_EWMA) * self._trend.get(pool, 0.0)
            self._trend[pool] = trend + TREND_EWMA * delta
            self._requantize_risk_locked(pool)
        anchor = self._anchor.get(pool)
        if anchor is None:
            # First sighting: anchor silently — the initial market snapshot
            # is not a reprice, or boot would storm one bump per pool.
            self._anchor[pool] = tick.discount
            return None
        if abs(tick.discount - anchor) < self.reprice_threshold * anchor:
            return None
        self._anchor[pool] = tick.discount
        self._generation += 1
        return Reprice(
            pool=pool,
            reason=REASON_PRICE,
            old_discount=anchor,
            new_discount=tick.discount,
            generation=self._generation,
        )

    # --- hazard -------------------------------------------------------------

    def note_interruption(self, pool: Pool) -> None:
        """An interruption landed on this pool (the interruption controller
        calls this at ingest): raise its hazard with a decaying count."""
        pool = tuple(pool)
        now = self.clock.now()
        with self._lock:
            self._interruptions[pool] = (
                self._decayed_locked(pool, now) + 1.0,
                now,
            )
            self._risk_generation += 1
            self._requantize_risk_locked(pool)

    def _decayed_locked(self, pool: Pool, now: float) -> float:
        entry = self._interruptions.get(pool)
        if entry is None:
            return 0.0
        count, stamp = entry
        return count * 0.5 ** ((now - stamp) / INTERRUPTION_HALF_LIFE_S)

    def pool_risk(self, pool: Pool) -> float:
        """Interruption hazard in [0, 1): depth-decline trend + recent
        observed interruptions. 0 for pools with no adverse signal."""
        pool = tuple(pool)
        now = self.clock.now()
        with self._lock:
            return self._risk_locked(pool, now)

    def _risk_locked(self, pool: Pool, now: float) -> float:
        decline = max(0.0, -self._trend.get(pool, 0.0))
        pressure = (
            TREND_WEIGHT * decline
            + INTERRUPTION_WEIGHT * self._decayed_locked(pool, now)
        )
        if pressure <= 0.0:
            return 0.0
        risk = 1.0 - math.exp(-pressure)
        # Quantize so the fleet-cache fingerprint only churns on material
        # moves, and so penalty columns are stable across jitter.
        return math.floor(risk / RISK_QUANTUM) * RISK_QUANTUM

    def _requantize_risk_locked(self, pool: Pool) -> None:
        quantized = self._risk_locked(pool, self.clock.now())
        if self._risk_q.get(pool, 0.0) != quantized:
            self._risk_q[pool] = quantized
            self._risk_generation += 1

    def has_risk(self) -> bool:
        """Cheap gate for the penalty hooks: False = every pool's risk is 0
        and the hooks skip entirely (bit-identical to no book at all)."""
        with self._lock:
            return any(q > 0.0 for q in self._risk_q.values())

    def risk_snapshot(self) -> Dict[Pool, float]:
        """Read-only quantized risk for every pool with any hazard state,
        under ONE lock acquisition and ONE clock read — the hot solve
        path's view (forecast.type_risks / risk_matrix loop over T x Z
        pools; per-pool pool_risk() calls would take the lock and the
        clock once per pool, contending with the market sweep's folds).
        Pools absent from the snapshot have risk 0, matching pool_risk."""
        now = self.clock.now()
        with self._lock:
            pools = (
                set(self._trend)
                | set(self._risk_q)
                | set(self._interruptions)
            )
            return {pool: self._risk_locked(pool, now) for pool in pools}

    def requantized_risks(self) -> Dict[Pool, float]:
        """Current quantized risk for every known pool, REQUANTIZING as
        time decays the interruption hazard: a pool that stops ticking
        would otherwise keep its last event-driven quantum forever —
        pool_risk() would read 0 while the fleet-cache fingerprint (and so
        the penalty the packer actually pays) stayed pinned at the old
        value. The market sweep calls this every cycle and publishes the
        result, so any quantum crossing (up OR down) bumps
        risk_generation and the caches track decay even for pools that
        never tick again."""
        now = self.clock.now()
        with self._lock:
            pools = (
                set(self._discount)
                | set(self._trend)
                | set(self._risk_q)
                | set(self._interruptions)
            )
            out: Dict[Pool, float] = {}
            for pool in pools:
                quantized = self._risk_locked(pool, now)
                if self._risk_q.get(pool, 0.0) != quantized:
                    self._risk_q[pool] = quantized
                    self._risk_generation += 1
                out[pool] = quantized
            return out

    # --- views --------------------------------------------------------------

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def risk_generation(self) -> int:
        with self._lock:
            return self._risk_generation

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._last_seq

    def fingerprint(self) -> Tuple[int, int]:
        with self._lock:
            return (self._generation, self._risk_generation)

    def spot_discount(self, pool: Pool) -> Optional[float]:
        with self._lock:
            return self._discount.get(tuple(pool))

    def is_closed(self, pool: Pool) -> bool:
        with self._lock:
            return tuple(pool) in self._closed

    def closed_since(self, pool: Pool) -> Optional[float]:
        """Feed time (tick.at) the pool's CURRENT ICE closure began; None if
        open. The drift sweep compares this against the feed's latest tick
        time to decide "ICE-closed past a sustained window" — transient
        blackouts (ordinary 45s ICE TTL churn) must not drift a fleet."""
        with self._lock:
            return self._closed_at.get(tuple(pool))

    def last_tick_at(self) -> Optional[float]:
        """Feed time of the newest applied tick (None until the first) — the
        clock domain closed_since lives in."""
        with self._lock:
            return self._last_tick_at

    def pools(self):
        with self._lock:
            return list(self._discount)

    def staleness_s(self, now: Optional[float] = None) -> float:
        """Feed-time age of the newest applied tick — the blackout signal
        (market_feed_staleness_seconds). 0 until the first tick lands."""
        with self._lock:
            if self._last_tick_at is None:
                return 0.0
            now = self.clock.now() if now is None else now
            return max(0.0, now - self._last_tick_at)

    def market(self) -> SpotMarket:
        """The current market as cloudprovider.market.SpotMarket — what
        ``simulate_plan_cost`` prices plans against (the capstone's
        post-spike oracle)."""
        with self._lock:
            return SpotMarket(
                discount=dict(self._discount), depth=dict(self._depth)
            )


# --- process-active book ------------------------------------------------------
#
# One book per controller process (Manager sets it at boot; a restarted
# Manager replaces it). GIL-atomic module slot, read lazily by the penalty
# hooks and the cache-key stampers so no solver-layer signature changes.

_active_book: Optional[PriceBook] = None


def set_active_book(book: Optional[PriceBook]) -> None:
    global _active_book
    _active_book = book


def active_book() -> Optional[PriceBook]:
    return _active_book


def active_fingerprint() -> Optional[Tuple[int, int]]:
    book = _active_book
    return None if book is None else book.fingerprint()


def active_generation() -> Optional[int]:
    book = _active_book
    return None if book is None else book.generation


def advertised_price(
    book: Optional["PriceBook"],
    pool: Pool,
    capacity_type: str,
    catalog_price: float,
    od_price: Optional[float] = None,
) -> Optional[float]:
    """THE advertised-repricing rule, shared by every provider's catalog
    path so the fake and EC2 backends cannot drift: no book / non-spot
    offering → the catalog price; an ICE-closed pool → None (the offering
    vanishes); a folded discount with an on-demand anchor → od × discount;
    no folded discount yet, or no anchor (a spot-only zone) → the catalog
    price untouched — a discount must never compound onto an
    already-discounted spot price."""
    from karpenter_tpu.api import wellknown

    if book is None or capacity_type != wellknown.CAPACITY_TYPE_SPOT:
        return catalog_price
    pool = tuple(pool)
    if book.is_closed(pool):
        return None
    discount = book.spot_discount(pool)
    if discount is None or od_price is None or od_price <= 0:
        return catalog_price
    return od_price * discount


def stamp_epoch(tag):
    """Combine a DeviceClusterState.compile_tag() with the market generation
    so a reprice invalidates PR 10's compiled-envelope cache: the cache keys
    on this value opaquely, and any generation bump changes it. None tags
    stay None (no caching)."""
    if tag is None:
        return None
    generation = active_generation()
    if generation is None:
        return tag
    return (tag, generation)


__all__ = [
    "PriceBook",
    "Reprice",
    "REASON_ICE",
    "REASON_PRICE",
    "advertised_price",
    "active_book",
    "active_fingerprint",
    "active_generation",
    "set_active_book",
    "stamp_epoch",
]
