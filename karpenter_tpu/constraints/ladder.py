"""The preference-relaxation ladder as data.

Ref: selection/preferences.go:64-106 — the reference relaxes a stuck pod one
step per retry (drop the heaviest preferred term, then drop leading required
OR-terms, never the last one) and re-runs the whole schedule at each step.
Here the SAME step sequence is materialized up front as an explicit list of
levels, so the constraint compiler can lower every level into one [L, G, T]
tensor and the pack kernel can solve them all in a single dispatch
(ops/pack_kernel.pack_kernel_levels), picking the strictest feasible level on
device instead of walking the ladder one 1-second requeue at a time.

Level 0 is the pod's full preference state; each subsequent level is exactly
one Preferences.Relax step further. The per-level *requirement view* mirrors
PodSpec.scheduling_requirements (node selector + heaviest remaining preferred
term + first remaining required OR-term) so level 0 of the ladder is
bit-identical to what the legacy one-shot path solved.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

from karpenter_tpu.api.pods import PodSpec, PreferredTerm
from karpenter_tpu.api.requirements import Requirement, Requirements

# Static cap on ladder depth: the L axis is a compiled tensor dimension, so a
# pathological pod with dozens of terms must not mint a fresh kernel bucket.
# The final state (everything droppable dropped) is always included, so
# capping only skips intermediate steps of absurd ladders.
MAX_LEVELS = 8


@dataclass(frozen=True)
class LadderState:
    """One relaxation level: the preferred/required terms still standing."""

    preferred: Tuple[PreferredTerm, ...]
    required: Tuple[Tuple[Requirement, ...], ...]

    def requirements(self, pod: PodSpec) -> Requirements:
        """The level's requirement view — scheduling_requirements() evaluated
        at this relaxation state (one definition, so the compiler and the
        scheduler's per-level validation cannot drift)."""
        requirements: List[Requirement] = [
            Requirement.in_(key, [value])
            for key, value in sorted(pod.node_selector.items())
        ]
        if self.preferred:
            heaviest = max(self.preferred, key=lambda term: term.weight)
            requirements.extend(heaviest.requirements)
        if self.required:
            requirements.extend(self.required[0])
        return Requirements(requirements)


@dataclass(frozen=True)
class RelaxationLadder:
    """All relaxation levels of one pod signature, strictest first."""

    states: Tuple[LadderState, ...]

    @property
    def num_levels(self) -> int:
        return len(self.states)

    def describe(self, level: int) -> str:
        if level >= self.num_levels:
            return "infeasible"
        state = self.states[min(level, self.num_levels - 1)]
        return (
            f"level {level}: {len(state.preferred)} preferred, "
            f"{len(state.required)} required terms"
        )

    def fingerprint(self) -> Tuple:
        """Hashable identity — part of the compiled-schedule signature."""
        return tuple(
            (
                tuple(
                    (t.weight, tuple((r.key, r.operator, r.values) for r in t.requirements))
                    for t in state.preferred
                ),
                tuple(
                    tuple((r.key, r.operator, r.values) for r in term)
                    for term in state.required
                ),
            )
            for state in self.states
        )


def build_ladder(pod: PodSpec, max_levels: int = MAX_LEVELS) -> RelaxationLadder:
    """Materialize the full Preferences.Relax step sequence for one pod.

    The step rule is a literal transcription of selection/preferences.go
    (and our former Preferences.advance): drop the heaviest preferred term
    while any remain, then drop leading required OR-terms down to the last
    one, which is never dropped."""
    preferred: List[PreferredTerm] = list(pod.preferred_terms)
    required: List[List[Requirement]] = [list(term) for term in pod.required_terms]
    states: List[LadderState] = [
        LadderState(tuple(preferred), tuple(tuple(t) for t in required))
    ]
    while True:
        if preferred:
            heaviest = max(preferred, key=lambda term: term.weight)
            preferred = [term for term in preferred if term is not heaviest]
        elif len(required) > 1:
            required = required[1:]
        else:
            break
        states.append(
            LadderState(tuple(preferred), tuple(tuple(t) for t in required))
        )
    if len(states) > max_levels:
        # Keep the strictest (max_levels - 1) states plus the fully-relaxed
        # terminal state — the two ends are what correctness depends on.
        states = states[: max_levels - 1] + [states[-1]]
    return RelaxationLadder(states=tuple(states))
