"""Constraint compiler subsystem: scheduling constraints as kernel tensors.

Lowers pod affinity/anti-affinity, topology-spread constraints (arbitrary
node-label keys), and the preference-relaxation ladder into device-resident
[L, G, T] masks/penalties, solved for every relaxation level in ONE kernel
dispatch with the strictest feasible level selected on device
(docs/design/constraint-compiler.md).

Layering:
    ladder.py    — the relaxation ladder as explicit levels
    compiler.py  — constraints -> [L, G, T] tensors (+ epoch-tagged cache)
    mirror.py    — bit-identical numpy twin of the kernel for host solvers
    solve.py     — dispatch + domain-pinned decode (the solve boundary)

Exports resolve lazily (PEP 562): compiler.py/solve.py pull in the jax
kernel stack, and the jax-free submodules (ladder, terms) must stay
importable without it — controllers/scheduling.py imports them at module
scope, and this __init__ runs on any submodule import.
"""

from __future__ import annotations

import os

_EXPORTS = {
    "CompiledConstraints": "compiler",
    "CompilerCache": "compiler",
    "compile_constraints": "compiler",
    "shared_cache": "compiler",
    "MAX_LEVELS": "ladder",
    "RelaxationLadder": "ladder",
    "build_ladder": "ladder",
    "ConstraintDecision": "solve",
    "decode_constrained": "solve",
    "solve_constrained": "solve",
}

__all__ = sorted(_EXPORTS) + ["greedy_topology_enabled"]


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f"{__name__}.{module}"), name)


def greedy_topology_enabled() -> bool:
    """True when KARPENTER_GREEDY_TOPOLOGY forces the legacy host-side
    Topology.inject pre-pass (kept for parity testing) instead of the
    compiled [L, G, T] path."""
    return os.environ.get("KARPENTER_GREEDY_TOPOLOGY", "").lower() in (
        "1",
        "true",
        "on",
    )
