"""Raw kube (anti-)affinity term helpers — jax-free, shared by the compiler
and the scheduler's signature builder (controllers/scheduling.py must stay
importable without pulling the kernel stack)."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from karpenter_tpu.api import wellknown


def node_domain(node, key: str) -> Optional[str]:
    """A node's domain value for one topology key — THE zone-vs-label
    fallback rule, shared by the compiler's domain discovery and the greedy
    oracle's Topology pass so the two can never diverge on what domain a
    node belongs to."""
    if key == wellknown.ZONE_LABEL:
        return node.zone or node.labels.get(key)
    return node.labels.get(key)


def term_topology_key(term: dict) -> str:
    return str(term.get("topologyKey") or term.get("topology_key") or "")


def term_match_labels(term: dict) -> Dict[str, str]:
    """Selector of a raw kube (anti-)affinity term dict; supports both the
    kube nesting ({"labelSelector": {"matchLabels": ...}}) and a flat
    {"matchLabels": ...}. Empty selector matches every pod."""
    selector = term.get("labelSelector") or {}
    labels = selector.get("matchLabels") or term.get("matchLabels") or {}
    return dict(labels)


def selector_matches(labels: Dict[str, str], pod_labels: Dict[str, str]) -> bool:
    return all(pod_labels.get(k) == v for k, v in labels.items())


def term_fingerprint(terms) -> Tuple:
    """Hashable identity of a term list — part of the compiled signature."""
    return tuple(
        sorted(
            (term_topology_key(t), tuple(sorted(term_match_labels(t).items())))
            for t in terms
        )
    )
