"""Host-side numpy mirror of the [L, G, T] constrained pack dispatch.

A literal transcription of ops/pack_kernel._fill_one_node_constrained /
_pack_one_level / pack_kernel_levels in numpy, with identical dtypes
(float32 ratios, the same _EPS floor) and identical first-index tie-breaks,
so the two paths produce bit-identical rounds. Host solvers (GreedySolver /
NativeSolver — the default in the test harness and the sub-break-even
dispatch tier) run constrained schedules through this mirror with no device
round trip; tests/test_constraints.py property-tests mirror == kernel on
random instances, which is what lets the two be used interchangeably.
"""

from __future__ import annotations

from typing import List, NamedTuple, Tuple

import numpy as np

from karpenter_tpu.ops.pack_kernel import max_rounds

_EPS = np.float32(1e-4)


class HostLevelPack(NamedTuple):
    """Mirror of ops/pack_kernel.LevelPack with host-native round lists."""

    rounds: List[Tuple[int, np.ndarray, int]]  # chosen level's (t, fill, repl)
    unschedulable: np.ndarray  # [G] int32 — chosen level's
    chosen_level: int
    group_level: np.ndarray  # [G] int32
    level_unsched: np.ndarray  # [L, G] int32
    overflow: bool


def _fill_one_node_host(capacity, vectors, counts, allow, conflict, node_cap):
    num_groups = vectors.shape[0]
    eligible = (counts > 0) & allow
    if not eligible.any():
        return np.zeros(num_groups, np.int32)
    first_eligible = int(np.argmax(eligible))
    remaining = capacity.astype(np.float32).copy()
    placed = np.zeros(num_groups, bool)
    packed = np.zeros(num_groups, np.int32)
    for g in range(num_groups):
        vec = vectors[g]
        cnt = int(counts[g])
        positive = vec > 0
        if positive.any():
            ratio = np.full(vec.shape, np.inf, np.float32)
            ratio[positive] = remaining[positive] / vec[positive]
            n_fit = int(np.floor(np.float32(ratio.min()) + _EPS))
        else:
            n_fit = np.iinfo(np.int32).max
        n_fit = max(n_fit, 0)
        conflicted = bool((placed & conflict[g]).any())
        allowed = bool(eligible[g]) and not conflicted
        n = min(cnt, n_fit, int(node_cap[g])) if allowed else 0
        if g == first_eligible and eligible[g] and not conflicted and n == 0:
            return np.zeros(num_groups, np.int32)  # abort: largest fits nowhere
        remaining -= np.float32(n) * vec
        if n > 0:
            placed[g] = True
        packed[g] = n
    return packed


def _pack_one_level_host(
    vectors, counts, capacity, valid_types, prices, allow, penalty,
    conflict, node_cap, mode: str,
):
    num_groups, num_types = vectors.shape[0], capacity.shape[0]
    mr = max_rounds(num_groups)
    fits = (vectors[:, None, :] <= capacity[None, :, :] + 1e-6).all(axis=-1)
    usable = allow & fits & valid_types[None, :]
    packable = usable.any(axis=1)
    unschedulable = np.where(packable, 0, counts).astype(np.int32)
    counts = np.where(packable, counts, 0).astype(np.int32)

    largest_valid = num_types - 1 - int(np.argmax(valid_types[::-1]))
    ref_cap = np.maximum(capacity[largest_valid], np.float32(1.0))
    group_weight = (vectors / ref_cap).max(axis=1)

    rounds: List[Tuple[int, np.ndarray, int]] = []
    packed_rounds = 0  # counts past mr too — overflow parity with the kernel
    iters = 0
    while counts.sum() > 0 and iters < mr + num_groups:
        iters += 1
        fills = np.stack(
            [
                _fill_one_node_host(
                    capacity[t], vectors, counts, usable[:, t], conflict, node_cap
                )
                if valid_types[t]
                else np.zeros(num_groups, np.int32)
                for t in range(num_types)
            ]
        )  # [T, G]
        sums = fills.sum(axis=1)
        packs_any = (sums > 0) & valid_types
        if mode == "ffd":
            bound = int(sums.max()) if num_types else 0
            achieves = (sums == bound) & valid_types & (bound > 0)
            t_sel = int(np.argmax(achieves))
            have_pack = bound > 0
        elif mode == "cost":
            weighted = fills.astype(np.float32) @ group_weight
            pen = (fills.astype(np.float32) * penalty.T).sum(axis=1)
            score = np.where(
                packs_any,
                (prices + pen) / np.maximum(weighted, np.float32(1e-9)),
                np.inf,
            )
            t_sel = int(np.argmin(score))
            have_pack = bool(packs_any.any())
        else:
            raise ValueError(f"unknown mode {mode!r}")
        if not have_pack:
            first_active = int(np.argmax(counts > 0))
            unschedulable[first_active] += counts[first_active]
            counts[first_active] = 0
            continue
        fill = fills[t_sel]
        safe = counts // np.maximum(fill, 1)
        repl_per_group = np.where(fill > 0, safe, np.iinfo(np.int32).max)
        repl = max(int(repl_per_group.min()), 1)
        counts = counts - repl * fill
        packed_rounds += 1
        if len(rounds) < mr:
            rounds.append((t_sel, fill.astype(np.int32), repl))
    # Overflow exactly as the kernel flags it: residual demand OR more
    # packed rounds than the static budget (the kernel's OOB scatter drops
    # the excess round; a silently-truncated plan must never decode as
    # complete, and the level-selection totals must agree bit-for-bit).
    return rounds, unschedulable, bool(counts.sum() > 0 or packed_rounds > mr)


def pack_levels_host(
    vectors,  # [G, R] f32
    level_counts,  # [L, G] i32
    capacity,  # [T, R] f32
    valid_types,  # [T] bool
    prices,  # [T] f32
    level_allow,  # [L, G, T] bool
    level_penalty,  # [L, G, T] f32
    conflict,  # [G, G] bool
    node_cap,  # [G] i32
    mode: str = "cost",
) -> HostLevelPack:
    """Host twin of pack_kernel_levels: identical level solve + strictest-
    feasible selection, returning the chosen level's rounds directly."""
    vectors = np.asarray(vectors, np.float32)
    capacity = np.asarray(capacity, np.float32)
    prices = np.asarray(prices, np.float32)
    num_levels, num_groups = level_counts.shape
    per_level = [
        _pack_one_level_host(
            vectors,
            level_counts[l],
            capacity,
            np.asarray(valid_types, bool),
            prices,
            np.asarray(level_allow[l], bool),
            np.asarray(level_penalty[l], np.float32),
            np.asarray(conflict, bool),
            np.asarray(node_cap, np.int32),
            mode,
        )
        for l in range(num_levels)
    ]
    level_unsched = np.stack([u for _, u, _ in per_level])  # [L, G]
    overflow = np.array([o for _, _, o in per_level], bool)
    # Miss count = unschedulable + assignment shortfall vs the fullest
    # level (see pack_kernel_levels — identical selection metric).
    assigned = level_counts.sum(axis=1)
    shortfall = assigned.max() - assigned
    totals = (
        level_unsched.sum(axis=1) + shortfall + overflow.astype(np.int64) * (2**30)
    )
    chosen = int(np.argmin(totals))
    feasible = (level_unsched == 0) & ~overflow[:, None]
    group_level = np.where(
        feasible.any(axis=0), np.argmax(feasible, axis=0), num_levels
    ).astype(np.int32)
    rounds, unschedulable, _ = per_level[chosen]
    return HostLevelPack(
        rounds=rounds,
        unschedulable=unschedulable,
        chosen_level=chosen,
        group_level=group_level,
        level_unsched=level_unsched,
        overflow=bool(overflow[chosen]),
    )
