"""The constraint compiler: scheduling constraints as device tensors.

Lowers one schedule's pod affinity/anti-affinity, topology-spread constraints
(arbitrary node-label keys, not just hostname/zone), and the full
preference-relaxation ladder (constraints/ladder.py) into the per-level
tensors the [L, G, T] pack dispatch consumes
(ops/pack_kernel.pack_kernel_levels):

  * `allow[l, g, t]`    — feasibility over (level, sub-group, type);
  * `penalty[l, g, t]`  — additive ScheduleAnyway spread pressure;
  * `level_counts[l,g]` — per-level pods per sub-group (domain-expanded
                          spread groups carry per-level water-filled takes);
  * `conflict[g, h]`    — may-not-share-a-node pairs (hostname
                          anti-affinity; sub-groups pinned to different
                          domains of one topology key);
  * `node_cap[g]`       — per-node caps (hostname spread: cap = max_skew;
                          hostname self-anti-affinity: cap = 1).

The lowering rules, by constraint family:

topology spread (DoNotSchedule)
    Hostname keys need no domain axis — fresh nodes ARE the domains — so a
    hostname constraint lowers to ``node_cap = max_skew`` (the greedy pass
    fabricated ceil(n/maxSkew) buckets of maxSkew pods each; a per-node cap
    is the same partition without the pre-solve selector injection). Any
    other key spreads over *domains* discovered from live node labels, the
    requirement envelope, and provisioner labels: each base pod-group
    expands into one sub-group per domain, and each level's pod counts are
    the closed-form water-fill of the batch over that level's allowed
    domains seeded with existing matching pods — exactly the greedy
    sequence's totals (TopologyGroup.assign_many), computed once at compile
    time. Sub-groups of different domains conflict (a node has one value
    per topology key), which keeps every node single-domain so decode can
    pin its launch pools (zone keys) or stamp its labels (custom keys).

topology spread (ScheduleAnyway)
    A soft constraint: no expansion, no mask — an additive penalty on types
    whose offered domains are already crowded, folded into the cost-mode
    round score.

pod anti-affinity
    Hostname terms become conflict-matrix entries (and a self-match becomes
    ``node_cap = 1``): provisioning only ever binds onto freshly launched
    nodes, so in-batch exclusion is the whole problem. Zone/custom-key
    terms exclude the domains where matching pods already run.

pod affinity
    Zone/custom-key terms restrict a level's domains to those hosting
    matching pods; when none exist yet but the batch itself contains
    matching pods, the batch seeds the domain (unrestricted) — the
    reference rejects these pods outright, so this is strictly new
    workload coverage. Hostname affinity stays rejected at selection.

relaxation ladder
    Level l's requirement view (ladder.states[l]) filters the fleet per
    level: instance-type/arch/os/capacity-type envelopes become rows of
    ``allow``; zone envelopes intersect into per-(level, sub-group) allowed
    zone sets that both mask types and pin launch pools at decode.
    Custom-label compatibility is level-validated host-side
    (Scheduler._compiled_signature) and arrives as ``valid_levels``.

The compiled *envelope* (everything independent of the concrete pod batch:
per-level type masks, zone sets, spread domains and their seed counts) is
cached under a lock keyed by (ladder, spread/affinity config, fleet
fingerprint, cluster tag) so repeated sweeps over an unchanged cluster
recompile nothing — the tag is the PR 7 incremental encoder's
(epoch, generation) pair (DeviceClusterState.compile_tag), which moves on
every delta flush: O(churn) invalidation, and no tag at all (no caching)
while deltas are still pending, since the envelope reads the live store.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import (
    DO_NOT_SCHEDULE,
    PodSpec,
    TopologySpreadConstraint,
)
from karpenter_tpu.constraints.ladder import RelaxationLadder
from karpenter_tpu.ops.encode import InstanceFleet, PodGroups
from karpenter_tpu.ops.pack_kernel import NODE_CAP_NONE

# ScheduleAnyway spread pressure per pod per excess matching pod in the
# type's least-crowded offered domain, in $/hr units (the cost-mode score is
# $/weighted-work; the penalty must be small against real node prices so it
# breaks ties instead of overriding economics).
SOFT_SPREAD_PENALTY = 0.005


from karpenter_tpu.constraints.terms import (  # noqa: E402 — shared helpers
    node_domain as _node_domain,
    selector_matches as _selector_matches,
    term_fingerprint,
    term_match_labels,
    term_topology_key,
)


@dataclass(frozen=True)
class SpreadDomains:
    """One domain-keyed topology constraint's discovered universe."""

    constraint: TopologySpreadConstraint
    domains: Tuple[str, ...]  # sorted
    seed_counts: Tuple[int, ...]  # existing matching pods per domain


@dataclass
class CompiledConstraints:
    """One schedule's constraints, lowered for the [L, G, T] dispatch."""

    ladder: RelaxationLadder
    valid_levels: List[bool]
    spread_key: Optional[str]  # the domain-expanded topology key, if any
    num_levels: int
    # Kernel tensors (host numpy; solve pads + uploads).
    vectors: np.ndarray  # [G', R] float32
    level_counts: np.ndarray  # [L, G'] int32
    allow: np.ndarray  # [L, G', T] bool
    penalty: np.ndarray  # [L, G', T] float32
    conflict: np.ndarray  # [G', G'] bool
    node_cap: np.ndarray  # [G'] int32
    # Decode metadata.
    sub_base: List[int]  # G' -> base group index
    sub_domain: List[Optional[str]]  # spread domain of each sub-group
    zone_sets: List[List[Optional[FrozenSet[str]]]]  # [L][G'] pool pinning
    members: List[List[List[PodSpec]]]  # [L][G'] pod lists per level
    epoch: Optional[int] = None

    @property
    def num_subgroups(self) -> int:
        return int(self.vectors.shape[0])


@dataclass(frozen=True)
class _Envelope:
    """The batch-independent compile product (cacheable): per-level type
    masks and zone sets plus the discovered spread domains."""

    type_mask: Tuple[Tuple[bool, ...], ...]  # [L][T]
    zone_sets: Tuple[Optional[FrozenSet[str]], ...]  # [L]
    spread: Optional[SpreadDomains]
    soft_spreads: Tuple[SpreadDomains, ...]
    # (Anti-)affinity lowers per topology key — a rack-keyed term must never
    # subtract rack values from a zone set. Zone-scoped terms restrict the
    # launch zones; spread-key-scoped terms restrict the expanded domains
    # (identical to the zone pair when the spread key IS the zone label).
    anti_excluded_zones: FrozenSet[str]
    affinity_zones: Optional[FrozenSet[str]]  # None = unrestricted
    spread_anti_excluded: FrozenSet[str]
    spread_affinity: Optional[FrozenSet[str]]  # None = unrestricted
    # Per-level allowed values of the expanded (non-zone) spread key — the
    # custom-key analogue of zone_sets. None = unrestricted at that level.
    spread_key_sets: Tuple[Optional[FrozenSet[str]], ...] = ()


class CompilerCache:
    """LRU of compiled envelopes, cluster-tag-tagged.

    Keyed by (schedule fingerprint, fleet fingerprint, cluster tag) where
    the tag is DeviceClusterState.compile_tag() — the (epoch, generation)
    pair that moves on every flushed watch delta, so pod/node churn
    naturally invalidates every entry: O(churn) bookkeeping, no scanning.
    Thread-safe: provisioning workers share one instance across sweeps."""

    MAX_ENTRIES = 128

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, _Envelope]" = OrderedDict()  # vet: guarded-by(self._lock)
        self.hits = 0  # vet: guarded-by(self._lock)
        self.misses = 0  # vet: guarded-by(self._lock)

    def get(self, key: Tuple) -> Optional[_Envelope]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
            return entry

    def put(self, key: Tuple, envelope: _Envelope) -> None:
        with self._lock:
            while len(self._entries) >= self.MAX_ENTRIES:
                self._entries.popitem(last=False)
            self._entries[key] = envelope

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


_shared_cache = CompilerCache()


def shared_cache() -> CompilerCache:
    return _shared_cache


def _fleet_fingerprint(fleet: InstanceFleet) -> Tuple:
    return (
        tuple(it.name for it in fleet.instance_types),
        fleet.capacity.tobytes(),
        tuple(fleet.allowed_zones),
        fleet.capacity_type,
    )


def _spread_fingerprint(rep: PodSpec) -> Tuple:
    return (
        tuple(c.group_key() for c in rep.topology_spread),
        term_fingerprint(rep.pod_affinity_terms),
        term_fingerprint(rep.pod_anti_affinity_terms),
    )


# --- domain discovery --------------------------------------------------------


def _domain_universe(key: str, allowed, constraints, fleet, cluster) -> set:
    """Candidate domain values for one topology key within the envelope."""
    domains = set()
    if key == wellknown.ZONE_LABEL:
        domains |= {z for z in (fleet.allowed_zones or []) if allowed.contains(z)}
        for it in fleet.instance_types:
            domains |= {z for z in it.zones() if allowed.contains(z)}
    if cluster is not None:
        for node in cluster.list_nodes():
            value = _node_domain(node, key)
            if value and allowed.contains(value):
                domains.add(value)
    finite = allowed.finite_values()
    if finite:
        domains |= set(finite)
    label_value = constraints.labels.get(key)
    if label_value and allowed.contains(label_value):
        domains.add(label_value)
    return domains


def _matching_pod_domains(cluster, key: str, matches) -> List[str]:
    """Domain value of every bound pod accepted by `matches` (one value per
    matching pod — callers count or set-ify as needed)."""
    values: List[str] = []
    if cluster is None:
        return values
    for pod in cluster.list_pods(
        predicate=lambda p: p.node_name is not None and matches(p.labels)
    ):
        node = cluster.try_get_node(pod.node_name)
        if node is None:
            continue
        value = _node_domain(node, key)
        if value:
            values.append(value)
    return values


def discover_domains(
    constraint: TopologySpreadConstraint,
    constraints,
    fleet: InstanceFleet,
    cluster,
    level_reqs=(),
) -> SpreadDomains:
    """The domain universe of one spread constraint: live node label values
    within the envelope, the envelope's own finite values, fleet zones (for
    the zone key), provisioner labels, and any finite values the ladder's
    level requirements name for the key (pod required/preferred terms) —
    the arbitrary-key generalization of Topology._compute_zonal. Empty =
    the constraint is ignored, matching the greedy pass's unknown-key
    behavior."""
    key = constraint.topology_key
    allowed = constraints.effective_requirements().allowed(key)
    universe = _domain_universe(key, allowed, constraints, fleet, cluster)
    for requirements in level_reqs:
        if requirements is None:
            continue
        finite = requirements.allowed(key).finite_values()
        if finite:
            universe |= {v for v in finite if allowed.contains(v)}
    ordered = tuple(sorted(universe))
    counts = [0] * len(ordered)
    index = {d: i for i, d in enumerate(ordered)}
    for value in _matching_pod_domains(cluster, key, constraint.matches):
        slot = index.get(value)
        if slot is not None:
            counts[slot] += 1
    return SpreadDomains(
        constraint=constraint, domains=ordered, seed_counts=tuple(counts)
    )


def water_fill_takes(seed_counts: Sequence[int], n: int) -> List[int]:
    """Per-domain takes of n sequential greedy argmin-count picks — the
    domain-total view of TopologyGroup.assign_many (same water level, same
    name-order tiebreak), shared so the compiled counts and the greedy
    fallback cannot drift."""
    if n <= 0 or not seed_counts:
        return [0] * len(seed_counts)
    counts = np.asarray(seed_counts, dtype=np.int64)
    lo, hi = int(counts.min()) + 1, int(counts.max()) + n
    while lo < hi:
        mid = (lo + hi) // 2
        if int(np.maximum(0, mid - counts).sum()) >= n:
            hi = mid
        else:
            lo = mid + 1
    level = lo
    full = np.maximum(0, (level - 1) - counts)
    remaining = n - int(full.sum())
    takes = full.copy()
    for i in range(len(counts)):
        if remaining == 0:
            break
        if counts[i] + full[i] == level - 1:
            takes[i] += 1
            remaining -= 1
    return [int(t) for t in takes]


# --- the compile -------------------------------------------------------------


def _level_type_mask(
    requirements, fleet: InstanceFleet, zone_set: Optional[FrozenSet[str]]
) -> List[bool]:
    """[T] — which fleet types satisfy one level's requirement view."""
    allowed_type = requirements.allowed(wellknown.INSTANCE_TYPE_LABEL)
    allowed_arch = requirements.allowed(wellknown.ARCH_LABEL)
    allowed_os = requirements.allowed(wellknown.OS_LABEL)
    allowed_cap = requirements.allowed(wellknown.CAPACITY_TYPE_LABEL)
    mask = []
    for it in fleet.instance_types:
        ok = (
            allowed_type.contains(it.name)
            and allowed_arch.contains(it.architecture)
            and any(allowed_os.contains(os) for os in it.operating_systems)
            and any(allowed_cap.contains(c) for c in it.capacity_types())
        )
        if ok and zone_set is not None:
            ok = any(z in zone_set for z in it.zones())
        mask.append(bool(ok))
    return mask


def _ladder_envelopes(schedule, rep: PodSpec, fleet: InstanceFleet):
    """Per-level (type mask, zone set, requirement view) from the ladder.
    Invalid levels carry None requirements and an all-False mask."""
    fleet_zones = set(fleet.allowed_zones or [])
    for it in fleet.instance_types:
        fleet_zones |= set(it.zones())
    zone_sets: List[Optional[FrozenSet[str]]] = []
    type_masks: List[Tuple[bool, ...]] = []
    level_reqs: List = []
    for level, state in enumerate(schedule.ladder.states):
        if not schedule.valid_levels[level]:
            zone_sets.append(frozenset())
            type_masks.append(tuple([False] * fleet.num_types))
            level_reqs.append(None)
            continue
        requirements = state.requirements(rep)
        level_reqs.append(requirements)
        allowed_zone = requirements.allowed(wellknown.ZONE_LABEL)
        if allowed_zone.is_any():
            zone_set: Optional[FrozenSet[str]] = None
        else:
            zone_set = frozenset(
                z for z in fleet_zones if allowed_zone.contains(z)
            )
        type_masks.append(tuple(_level_type_mask(requirements, fleet, zone_set)))
        zone_sets.append(zone_set)
    return type_masks, zone_sets, level_reqs


def _key_sets_per_level(key: str, level_reqs) -> Tuple[Optional[FrozenSet[str]], ...]:
    """[L] allowed values of one label key per ladder level (None = any;
    invalid levels get the empty set)."""
    sets: List[Optional[FrozenSet[str]]] = []
    for requirements in level_reqs:
        if requirements is None:
            sets.append(frozenset())
            continue
        allowed = requirements.allowed(key)
        finite = allowed.finite_values()
        sets.append(None if finite is None else frozenset(finite))
    return tuple(sets)


def _spread_discovery(rep: PodSpec, constraints, fleet, cluster, level_reqs=()):
    """(hard spread to expand, soft spreads): hostname keys lower to node
    caps (handled by _hostname_caps); the first hard domain-keyed
    constraint expands; ScheduleAnyway and later hard ones become soft
    penalties. The ladder's per-level requirement views contribute their
    finite values to the domain universe — pod-level required terms live in
    the ladder, not the schedule envelope."""
    spread: Optional[SpreadDomains] = None
    soft: List[SpreadDomains] = []
    for constraint in rep.topology_spread:
        if constraint.topology_key == wellknown.HOSTNAME_LABEL:
            continue
        discovered = discover_domains(
            constraint, constraints, fleet, cluster, level_reqs=level_reqs
        )
        if not discovered.domains:
            continue  # unknown key with no domains: ignored (greedy parity)
        if constraint.when_unsatisfiable == DO_NOT_SCHEDULE and spread is None:
            spread = discovered
        else:
            if constraint.when_unsatisfiable == DO_NOT_SCHEDULE:
                # Only ONE hard domain-keyed constraint gets the expansion
                # axis; further ones degrade to best-effort penalties (and
                # contribute nothing for non-zone keys). Loud, not silent:
                # a violated hard constraint must be traceable to this
                # demotion. (ROADMAP: constraint-compiler follow-ons.)
                from karpenter_tpu.utils import logging as klog

                klog.named("constraints").warning(
                    "second hard spread constraint on %r demoted to "
                    "best-effort (one domain-expanded key per schedule)",
                    constraint.topology_key,
                )
            soft.append(discovered)
    return spread, soft


def _anti_affinity_exclusions(rep: PodSpec, cluster, key: str) -> FrozenSet[str]:
    """Domains of ONE topology key excluded by anti-affinity: wherever
    matching pods run. Key-scoped — a rack-keyed exclusion must never
    subtract rack values from a ZONE domain set (the value namespaces are
    unrelated, so cross-key mixing silently drops or nukes constraints)."""
    excluded: set = set()
    for term in rep.pod_anti_affinity_terms:
        if term_topology_key(term) != key:
            continue
        labels = term_match_labels(term)
        excluded.update(
            _matching_pod_domains(
                cluster, key, lambda pl, _l=labels: _selector_matches(_l, pl)
            )
        )
    return frozenset(excluded)


def _affinity_inclusions(
    rep: PodSpec, cluster, key: str
) -> Optional[FrozenSet[str]]:
    """Domains of ONE topology key required by affinity (∩ across that
    key's terms); None = unrestricted — including the batch-seeding case
    where no targets exist yet. Key-scoped for the same reason as
    _anti_affinity_exclusions."""
    affinity_domains: Optional[FrozenSet[str]] = None
    for term in rep.pod_affinity_terms:
        if term_topology_key(term) != key:
            continue
        labels = term_match_labels(term)
        found = frozenset(
            _matching_pod_domains(
                cluster, key, lambda pl, _l=labels: _selector_matches(_l, pl)
            )
        )
        if found:
            affinity_domains = (
                found if affinity_domains is None else affinity_domains & found
            )
        # else: batch-seeded — no restriction from this term.
    return affinity_domains


def _compile_envelope(
    schedule, rep: PodSpec, fleet: InstanceFleet, cluster
) -> _Envelope:
    type_masks, zone_sets, level_reqs = _ladder_envelopes(schedule, rep, fleet)
    spread, soft = _spread_discovery(
        rep, schedule.constraints, fleet, cluster, level_reqs=level_reqs
    )
    anti_zones = _anti_affinity_exclusions(rep, cluster, wellknown.ZONE_LABEL)
    affinity_zones = _affinity_inclusions(rep, cluster, wellknown.ZONE_LABEL)
    key_sets: Tuple[Optional[FrozenSet[str]], ...] = ()
    spread_anti, spread_affinity = anti_zones, affinity_zones
    if spread is not None and spread.constraint.topology_key != wellknown.ZONE_LABEL:
        key = spread.constraint.topology_key
        key_sets = _key_sets_per_level(key, level_reqs)
        spread_anti = _anti_affinity_exclusions(rep, cluster, key)
        spread_affinity = _affinity_inclusions(rep, cluster, key)
    return _Envelope(
        type_mask=tuple(type_masks),
        zone_sets=tuple(zone_sets),
        spread=spread,
        soft_spreads=tuple(soft),
        anti_excluded_zones=anti_zones,
        affinity_zones=affinity_zones,
        spread_anti_excluded=spread_anti,
        spread_affinity=spread_affinity,
        spread_key_sets=key_sets,
    )


def _hostname_caps(rep: PodSpec) -> int:
    """Per-node cap from hostname spread + hostname self-anti-affinity."""
    cap = NODE_CAP_NONE
    for constraint in rep.topology_spread:
        if (
            constraint.topology_key == wellknown.HOSTNAME_LABEL
            and constraint.when_unsatisfiable == DO_NOT_SCHEDULE
        ):
            cap = min(cap, max(int(constraint.max_skew), 1))
    for term in rep.pod_anti_affinity_terms:
        if term_topology_key(term) == wellknown.HOSTNAME_LABEL:
            if _selector_matches(term_match_labels(term), rep.labels):
                cap = 1
    return cap


def _soft_penalties(envelope: _Envelope, type_zones, num_types: int) -> np.ndarray:
    """[T] ScheduleAnyway spread pressure: per type, the crowding of its
    least-crowded offered zone relative to the global minimum."""
    soft_pen = np.zeros((num_types,), np.float32)
    for discovered in envelope.soft_spreads:
        if discovered.constraint.topology_key != wellknown.ZONE_LABEL:
            continue
        counts = dict(zip(discovered.domains, discovered.seed_counts))
        floor = min(counts.values()) if counts else 0
        for t, zones in enumerate(type_zones):
            offered = [counts[z] for z in zones if z in counts]
            if offered:
                soft_pen[t] += SOFT_SPREAD_PENALTY * (min(offered) - floor)
    return soft_pen


def _build_conflicts(
    rep: PodSpec, num_sub: int, sub_domain, spread: Optional[SpreadDomains]
) -> np.ndarray:
    """[G', G'] may-not-share-a-node pairs: sub-groups pinned to different
    domains of the expanded key (one label value per node), plus
    SELF-MATCHED hostname anti-affinity forbidding co-residence across
    groups (all schedule pods share labels when anti-affinity is in the
    signature, so the rep's self-match speaks for every member). A
    hostname term targeting OTHER labels is vacuous in-batch — its targets
    merge into different schedules, which launch different fresh nodes —
    and must not fragment this schedule's pack one-group-per-node."""
    conflict = np.zeros((num_sub, num_sub), bool)
    if spread is not None:
        for a in range(num_sub):
            for b in range(num_sub):
                if sub_domain[a] != sub_domain[b]:
                    conflict[a, b] = True
    if any(
        term_topology_key(t) == wellknown.HOSTNAME_LABEL
        and _selector_matches(term_match_labels(t), rep.labels)
        for t in rep.pod_anti_affinity_terms
    ):
        conflict |= ~np.eye(num_sub, dtype=bool)
    return conflict


@dataclass
class _LevelFiller:
    """Fills one level's slices of the compiled tensors (counts/allow/
    penalty) and produces that level's member splits + zone pins — the
    per-level lowering loop of compile_constraints, split by spread regime."""

    envelope: _Envelope
    groups: PodGroups
    spread: Optional[SpreadDomains]
    spread_is_zonal: bool
    type_zones: List[FrozenSet[str]]
    soft_pen: np.ndarray
    sub_base: List[int]
    sub_domain: List[Optional[str]]
    level_counts: np.ndarray
    allow: np.ndarray
    penalty: np.ndarray

    def fill(self, level: int):
        if self.spread is not None:
            return self._fill_spread(level)
        return self._fill_plain(level)

    def _zone_type_mask(self, zone: FrozenSet[str]) -> np.ndarray:
        return np.array([bool(tz & zone) for tz in self.type_zones], bool)

    def _zone_restriction(self, level: int) -> Optional[FrozenSet[str]]:
        """One level's zone-scoped restriction: ladder zone envelope ∩
        affinity inclusions − anti-affinity exclusions. None = any.
        Shared by the plain path and custom-key spread rounds — a rack
        spread's domain axis is not zones, so zone-keyed terms must still
        restrict its types and pin its pools."""
        zone = self.envelope.zone_sets[level]
        if self.envelope.affinity_zones is not None:
            zone = (
                self.envelope.affinity_zones
                if zone is None
                else zone & self.envelope.affinity_zones
            )
        if self.envelope.anti_excluded_zones:
            base = zone if zone is not None else frozenset(
                z for tz in self.type_zones for z in tz
            )
            zone = frozenset(base - self.envelope.anti_excluded_zones)
        return zone

    def _allowed_domains(self, level: int, level_zone) -> List[str]:
        """Domains this level admits: the ladder's envelope for the spread
        key (zone set for zone-keyed spreads, the level's finite key values
        for custom keys), minus anti-affinity exclusions, intersected with
        affinity inclusions."""
        key_sets = self.envelope.spread_key_sets
        key_set = key_sets[level] if key_sets else None
        allowed = []
        for d in self.spread.domains:
            if d in self.envelope.spread_anti_excluded:
                continue
            if self.spread_is_zonal:
                if level_zone is not None and d not in level_zone:
                    continue
            elif key_set is not None and d not in key_set:
                continue
            inclusions = self.envelope.spread_affinity
            if inclusions is not None and d not in inclusions:
                continue
            allowed.append(d)
        return allowed

    def _fill_spread(self, level: int):
        num_sub = len(self.sub_base)
        level_zone = self.envelope.zone_sets[level]
        type_mask = np.array(self.envelope.type_mask[level], bool)
        level_members: List[List[PodSpec]] = [[] for _ in range(num_sub)]
        level_zone_sets: List[Optional[FrozenSet[str]]] = [None] * num_sub
        allowed_domains = self._allowed_domains(level, level_zone)
        domain_index = {d: i for i, d in enumerate(self.spread.domains)}
        # Per base group, water-fill the group's pods over the allowed
        # domains — seeded with existing pods, carrying counts across groups
        # in FFD order so the whole schedule's totals match the greedy
        # sequence.
        running = {
            d: self.spread.seed_counts[domain_index[d]] for d in allowed_domains
        }
        for g in range(self.groups.num_groups):
            pod_list = self.groups.members[g]
            takes = water_fill_takes(
                [running[d] for d in allowed_domains], len(pod_list)
            )
            cursor = 0
            for di, d in enumerate(allowed_domains):
                sub = g * len(self.spread.domains) + domain_index[d]
                take = takes[di]
                self.level_counts[level, sub] = take
                level_members[sub] = pod_list[cursor : cursor + take]
                cursor += take
                running[d] += take
                if self.spread_is_zonal:
                    zone = frozenset([d])
                    if level_zone is not None:
                        zone = zone & level_zone
                    level_zone_sets[sub] = zone
        zone_restrict = None if self.spread_is_zonal else self._zone_restriction(level)
        for sub in range(num_sub):
            d = self.sub_domain[sub]
            if d not in allowed_domains:
                continue
            self.allow[level, sub] = type_mask
            if self.spread_is_zonal:
                zone = level_zone_sets[sub] or frozenset([d])
                self.allow[level, sub] &= self._zone_type_mask(zone)
            elif zone_restrict is not None:
                # Custom-key spread: the domain axis is not zones, so the
                # level's zone-scoped terms restrict types AND pin pools.
                self.allow[level, sub] &= self._zone_type_mask(zone_restrict)
                level_zone_sets[sub] = zone_restrict
            self.penalty[level, sub] = self.soft_pen
        return level_zone_sets, level_members

    def _fill_plain(self, level: int):
        num_sub = len(self.sub_base)
        type_mask = np.array(self.envelope.type_mask[level], bool)
        level_members: List[List[PodSpec]] = [[] for _ in range(num_sub)]
        zone = self._zone_restriction(level)
        for sub in range(num_sub):
            self.level_counts[level, sub] = int(
                self.groups.counts[self.sub_base[sub]]
            )
            level_members[sub] = self.groups.members[self.sub_base[sub]]
            self.allow[level, sub] = type_mask
            if zone is not None:
                self.allow[level, sub] &= self._zone_type_mask(zone)
            self.penalty[level, sub] = self.soft_pen
        return [zone] * num_sub, level_members


def compile_constraints(
    schedule,
    groups: PodGroups,
    fleet: InstanceFleet,
    cluster=None,
    cache: Optional[CompilerCache] = None,
    epoch: Optional[int] = None,
) -> CompiledConstraints:
    """Lower one schedule's constraints against a concrete fleet.

    `schedule` must carry `ladder`, `valid_levels`, and `constraints`
    (controllers/scheduling.Schedule on the compiled path). `epoch` is the
    incremental encoder's cluster tag (compile_tag's (epoch, generation)
    pair) when available; with both `cache` and `epoch` the
    batch-independent envelope is reused across sweeps."""
    rep = schedule.rep if getattr(schedule, "rep", None) is not None else schedule.pods[0]
    ladder: RelaxationLadder = schedule.ladder
    num_levels = ladder.num_levels
    num_types = fleet.num_types

    envelope: Optional[_Envelope] = None
    key: Optional[Tuple] = None
    if cache is not None and epoch is not None:
        key = (
            ladder.fingerprint(),
            tuple(schedule.valid_levels),
            _spread_fingerprint(rep),
            _fleet_fingerprint(fleet),
            # The envelope reads the schedule constraints too (domain
            # discovery consults provisioner labels + requirements): two
            # provisioners sharing a fleet — or one whose spec changed
            # without any pod/node churn — must not share entries.
            tuple(sorted(schedule.constraints.labels.items())),
            schedule.constraints.requirements.canonical_key(),
            epoch,
        )
        envelope = cache.get(key)
    if envelope is None:
        envelope = _compile_envelope(schedule, rep, fleet, cluster)
        if cache is not None and key is not None:
            cache.put(key, envelope)

    spread = envelope.spread
    node_cap_value = _hostname_caps(rep)

    # Sub-group expansion over the spread domains (if any).
    sub_base: List[int] = []
    sub_domain: List[Optional[str]] = []
    if spread is not None:
        for g in range(groups.num_groups):
            for domain in spread.domains:
                sub_base.append(g)
                sub_domain.append(domain)
    else:
        sub_base = list(range(groups.num_groups))
        sub_domain = [None] * groups.num_groups
    num_sub = len(sub_base)

    vectors = (
        groups.vectors[sub_base]
        if num_sub
        else np.zeros((0, groups.vectors.shape[1]), np.float32)
    )
    level_counts = np.zeros((num_levels, num_sub), np.int32)
    allow = np.zeros((num_levels, num_sub, num_types), bool)
    penalty = np.zeros((num_levels, num_sub, num_types), np.float32)
    zone_sets: List[List[Optional[FrozenSet[str]]]] = []
    members: List[List[List[PodSpec]]] = []

    spread_is_zonal = (
        spread is not None
        and spread.constraint.topology_key == wellknown.ZONE_LABEL
    )
    type_zones = [frozenset(it.zones()) for it in fleet.instance_types]
    soft_pen = _soft_penalties(envelope, type_zones, num_types)

    filler = _LevelFiller(
        envelope=envelope,
        groups=groups,
        spread=spread,
        spread_is_zonal=spread_is_zonal,
        type_zones=type_zones,
        soft_pen=soft_pen,
        sub_base=sub_base,
        sub_domain=sub_domain,
        level_counts=level_counts,
        allow=allow,
        penalty=penalty,
    )
    for level in range(num_levels):
        level_zone_sets, level_members = filler.fill(level)
        zone_sets.append(level_zone_sets)
        members.append(level_members)

    conflict = _build_conflicts(rep, num_sub, sub_domain, spread)
    node_cap = np.full((num_sub,), node_cap_value, np.int32)
    return CompiledConstraints(
        ladder=ladder,
        valid_levels=list(schedule.valid_levels),
        spread_key=spread.constraint.topology_key if spread else None,
        num_levels=num_levels,
        vectors=vectors.astype(np.float32),
        level_counts=level_counts,
        allow=allow,
        penalty=penalty,
        conflict=conflict,
        node_cap=node_cap,
        sub_base=sub_base,
        sub_domain=sub_domain,
        zone_sets=zone_sets,
        members=members,
        epoch=epoch,
    )
