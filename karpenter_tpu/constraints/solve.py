"""Constrained solve: one [L, G, T] dispatch, then domain-aware decode.

The execution layer over the compiler (constraints/compiler.py): pad the
compiled tensors, run EVERY relaxation level in one jitted dispatch
(ops/pack_kernel.pack_kernel_levels on device solvers, the bit-identical
numpy mirror on host solvers), then decode the chosen level's rounds into
Packings whose launch pools are pinned to each node's spread domain / ladder
zone envelope — replacing both the serialized Topology.inject pre-pass and
the host-side relax-retry loop with a single solve whose decode names the
chosen relaxation level per group.

Zone-keyed domains realize as pool pinning (the launch lands in the domain);
custom-label domains realize as node labels stamped at registration
(ffd.Packing.node_labels) — fresh nodes are born into their domain, which is
strictly more than the reference's "existing zones only" spread.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.constraints.compiler import (
    CompiledConstraints,
    CompilerCache,
    compile_constraints,
    shared_cache,
)
from karpenter_tpu.constraints.mirror import pack_levels_host
from karpenter_tpu.ops import ffd
from karpenter_tpu.ops.encode import InstanceFleet, build_fleet, group_pods
from karpenter_tpu.ops.pack_kernel import (
    NODE_CAP_NONE,
    bucket_size,
    pack_kernel_levels,
    pad_to,
)
from karpenter_tpu.utils.metrics import REGISTRY

# Which relaxation level constrained solves land on — a rising count at
# level > 0 means preferences are routinely unsatisfiable (capacity does not
# match what workloads prefer), the signal the reference could never surface
# because its relaxation was scattered across retries.
CONSTRAINT_LEVEL_TOTAL = REGISTRY.counter(
    "constraint_solve_level_total",
    "Constrained solves by kernel-chosen relaxation level",
    ["level"],
)
CONSTRAINT_DISPATCH_TOTAL = REGISTRY.counter(
    "constraint_dispatch_total",
    "Constrained solves by dispatch path (kernel|mirror)",
    ["path"],
)


@dataclass
class ConstraintDecision:
    """What the [L, G, T] dispatch decided, for bookkeeping: the schedule's
    chosen level plus per-base-group first-feasible levels. The selection
    controller's TTL cache records pod_levels instead of driving retries."""

    chosen_level: int
    group_levels: List[int]  # per base group (min over its sub-groups)
    pod_levels: Dict[str, int] = field(default_factory=dict)  # uid -> level
    description: str = ""


def _solve_mode(solver) -> str:
    mode = getattr(solver, "mode", None)
    if mode in ("ffd", "cost"):
        return mode
    return "cost" if getattr(solver, "needs_device_warmup", False) else "ffd"


def _dispatch_kernel(compiled: CompiledConstraints, fleet: InstanceFleet, mode: str):
    """Pad + run the jitted [L, G, T] dispatch; one device->host fetch."""
    from karpenter_tpu.models.solver import _to_host
    from karpenter_tpu.models.solver import constrained_level_hook

    num_sub = compiled.num_subgroups
    num_levels = compiled.num_levels
    g_pad = bucket_size(max(num_sub, 1))
    t_pad = bucket_size(max(fleet.num_types, 1))
    l_pad = bucket_size(max(num_levels, 1), minimum=1)

    vectors = pad_to(compiled.vectors, g_pad)
    counts = pad_to(pad_to(compiled.level_counts, g_pad, axis=1), l_pad)
    # Padded levels repeat the last real level: identical totals, and the
    # strictest-first argmin can never pick a phantom level over a real one.
    if l_pad > num_levels:
        counts[num_levels:] = counts[num_levels - 1]
    allow = pad_to(pad_to(compiled.allow, g_pad, axis=1), t_pad, axis=2)
    allow = pad_to(allow, l_pad)
    penalty = pad_to(pad_to(compiled.penalty, g_pad, axis=1), t_pad, axis=2)
    penalty = pad_to(penalty, l_pad)
    if l_pad > num_levels:
        allow[num_levels:] = allow[num_levels - 1]
        penalty[num_levels:] = penalty[num_levels - 1]
    conflict = pad_to(pad_to(compiled.conflict, g_pad), g_pad, axis=1)
    node_cap = pad_to(compiled.node_cap, g_pad, value=NODE_CAP_NONE)
    capacity = pad_to(fleet.capacity, t_pad)
    total = pad_to(fleet.total, t_pad)
    valid = pad_to(np.ones(fleet.num_types, bool), t_pad)
    prices = pad_to(fleet.prices, t_pad)

    constrain, shards = constrained_level_hook()
    pack = pack_kernel_levels(
        vectors, counts, capacity, total, valid, prices,
        allow, penalty, conflict, node_cap,
        mode=mode, constrain=constrain,
    )
    try:
        host = _to_host(pack)
    except Exception as error:  # noqa: BLE001 — quarantine, then re-raise
        # Same hook as fetch_plans: dispatch is async, so a chip that dies
        # during the L-axis-sharded solve surfaces at this fetch. The
        # quarantine shrinks the mesh for the NEXT constrained dispatch
        # (the pods stay pending and heal through that sweep); without it
        # every constrained solve would re-fail on the dead chip forever.
        if shards > 1:
            from karpenter_tpu.models.solver import quarantine_devices

            quarantine_devices(error)
        raise
    num_rounds = min(int(host.rounds.num_rounds), int(host.rounds.round_type.shape[0]))
    rounds = [
        (
            int(host.rounds.round_type[r]),
            host.rounds.round_fill[r, :num_sub],
            int(host.rounds.round_repl[r]),
        )
        for r in range(num_rounds)
    ]
    return (
        rounds,
        host.rounds.unschedulable[:num_sub],
        int(host.chosen_level),
        host.group_level[:num_sub],
        bool(host.rounds.overflow),
        shards,
    )


def _dispatch_mirror(compiled: CompiledConstraints, fleet: InstanceFleet, mode: str):
    pack = pack_levels_host(
        compiled.vectors,
        compiled.level_counts,
        fleet.capacity,
        np.ones(fleet.num_types, bool),
        fleet.prices,
        compiled.allow,
        compiled.penalty,
        compiled.conflict,
        compiled.node_cap,
        mode=mode,
    )
    num_sub = compiled.num_subgroups
    return (
        pack.rounds,
        pack.unschedulable[:num_sub],
        int(pack.chosen_level),
        pack.group_level[:num_sub],
        pack.overflow,
        1,
    )


def decode_constrained(
    rounds: List[Tuple[int, np.ndarray, int]],
    unschedulable: np.ndarray,
    compiled: CompiledConstraints,
    level: int,
    fleet: InstanceFleet,
) -> ffd.PackResult:
    """Chosen-level rounds -> Packings with domain-pinned launch pools.

    Mirrors models/solver._decode_rounds (lazy member windows, merge by
    option key) plus the constraint realization: every sub-group active in a
    round shares one domain (the conflict matrix forbade mixing), so the
    round's pools pin to the intersection of its sub-groups' allowed zones,
    and custom-key domains stamp node labels."""
    from karpenter_tpu.models.solver import _pool_price_matrix, sort_pool_rows

    level = min(level, compiled.num_levels - 1)
    members = compiled.members[level]
    num_sub = compiled.num_subgroups
    zones, pool_prices = _pool_price_matrix(fleet)
    pool_order = sort_pool_rows(pool_prices)

    cursors = [0] * num_sub
    by_key: Dict[Tuple, ffd.Packing] = {}
    packings: List[ffd.Packing] = []
    unsched_pods: List[PodSpec] = []
    for t, fill, repl in rounds:
        fill = np.asarray(fill)[:num_sub]  # vet: host-array(decode runs on fetched rounds)
        active = np.nonzero(fill > 0)[0]
        if active.size == 0:
            continue
        zone_restrict, node_labels = _round_realization(compiled, level, active)
        options, pool_opts = _round_pools(
            fill, t, compiled, fleet, zones, pool_prices, pool_order, zone_restrict
        )
        repl = int(repl)
        if options is None:
            # No pool survives the round's hard zone pin: the pods stay
            # pending and heal through a later sweep's fresh compile.
            for sub in active:
                sub, n = int(sub), int(fill[sub]) * repl
                unsched_pods.extend(members[sub][cursors[sub] : cursors[sub] + n])
                cursors[sub] += n
            continue
        slices = []
        for sub in active:
            sub, n = int(sub), int(fill[sub])
            slices.append((sub, cursors[sub], n))
            cursors[sub] += n * repl
        key = (
            tuple(it.name for it in options),
            tuple((p.instance_type.name, p.zone) for p in pool_opts)
            if pool_opts
            else None,
            tuple(sorted(node_labels.items())),
        )
        existing = by_key.get(key)
        if existing is not None:
            existing.node_quantity += repl
            existing.pods_per_node.add_segment(repl, slices)
        else:
            lazy = ffd.LazyNodePods(members)
            lazy.add_segment(repl, slices)
            packing = ffd.Packing(
                pods_per_node=lazy,
                instance_type_options=list(options),
                node_quantity=repl,
                pool_options=pool_opts,
                node_labels=dict(node_labels) or None,
            )
            by_key[key] = packing
            packings.append(packing)

    for sub in np.nonzero(np.asarray(unschedulable)[:num_sub] > 0)[0]:  # vet: host-array(decode runs on fetched rounds)
        sub = int(sub)
        n = int(unschedulable[sub])
        unsched_pods.extend(members[sub][cursors[sub] : cursors[sub] + n])
        cursors[sub] += n
    return ffd.PackResult(packings=packings, unschedulable=unsched_pods)


def _round_realization(compiled: CompiledConstraints, level: int, active):
    """(zone restriction, node labels) of one round: every active sub-group
    shares a domain (the conflict matrix forbade mixing), so zone pins
    intersect and custom-key domains stamp labels."""
    zone_sets = compiled.zone_sets[level]
    zone_restrict = None
    node_labels: Dict[str, str] = {}
    for sub in active:
        zs = zone_sets[int(sub)]
        if zs is not None:
            zone_restrict = zs if zone_restrict is None else zone_restrict & zs
        domain = compiled.sub_domain[int(sub)]
        if (
            domain is not None
            and compiled.spread_key
            and compiled.spread_key != wellknown.ZONE_LABEL
        ):
            node_labels[compiled.spread_key] = domain
    return zone_restrict, node_labels


def _round_pools(
    fill, t, compiled, fleet, zones, pool_prices, pool_order, zone_restrict
):
    """Price-ranked launch options for one round, pinned to its zone
    restriction. (None, None) when no pool survives the pin (e.g. the
    pinned zones are in the ICE blackout): the round must NOT launch
    unpinned — that would land in a domain the chosen level's spread or
    anti-affinity forbids — so its pods stay pending instead."""
    from karpenter_tpu.models.solver import (
        _cheapest_feasible_pools,
        pool_rows_to_options,
    )

    rows = None
    if zone_restrict is not None and len(zone_restrict) < len(zones):
        pinned = pool_prices.copy()
        for j, z in enumerate(zones):
            if z not in zone_restrict:
                pinned[:, j] = np.inf
        if not np.isfinite(pinned).any():
            return None, None
        type_indices, rows = _cheapest_feasible_pools(
            fill, t, compiled.vectors, fleet.capacity, pinned
        )
    else:
        type_indices, rows = _cheapest_feasible_pools(
            fill, t, compiled.vectors, fleet.capacity, pool_prices, pool_order
        )
    options = [fleet.instance_types[i] for i in type_indices]
    return options, pool_rows_to_options(rows, fleet, zones)


def _dropped_pods(
    compiled: CompiledConstraints, groups, chosen: int
) -> List[PodSpec]:
    """Pods absent from EVERY sub-group's counts at the chosen level — e.g.
    anti-affinity excluded every spread domain, so the level filler's
    water-fill took zero pods. They never reached the kernel, whose
    unschedulable column only covers counted-but-unpacked pods; without this
    they would vanish from the result (neither packed nor reported) while
    still being recorded as solved. The filler assigns each group's pod list
    in order, so the dropped remainder is the tail past the level's total."""
    level = min(chosen, compiled.num_levels - 1)
    level_totals = [0] * groups.num_groups
    for sub, base in enumerate(compiled.sub_base):
        level_totals[base] += int(compiled.level_counts[level, sub])
    dropped: List[PodSpec] = []
    for g in range(groups.num_groups):
        dropped.extend(groups.members[g][level_totals[g]:])
    return dropped


def solve_constrained(
    solver,
    schedule,
    instance_types,
    daemons: Sequence[PodSpec] = (),
    cluster=None,
    cache: Optional[CompilerCache] = None,
    epoch: Optional[int] = None,
) -> Tuple[ffd.PackResult, ConstraintDecision]:
    """Solve one compiled schedule end-to-end: compile -> [L, G, T] dispatch
    -> domain-pinned decode. Device-backed solvers run the jitted kernel;
    host solvers run the bit-identical numpy mirror."""
    groups = group_pods(list(schedule.pods))
    pods_need = (
        groups.vectors.max(axis=0) if groups.num_groups else None
    )
    fleet = build_fleet(
        instance_types, schedule.constraints, schedule.pods, daemons,
        pods_need=pods_need,
    )
    trivial = ConstraintDecision(
        chosen_level=0, group_levels=[0] * groups.num_groups
    )
    if fleet.num_types == 0 or groups.num_groups == 0:
        return ffd.pack_groups(fleet, groups), trivial

    compiled = compile_constraints(
        schedule, groups, fleet, cluster,
        cache=cache or shared_cache(), epoch=epoch,
    )
    if compiled.num_subgroups == 0:
        return ffd.pack_groups(fleet, groups), trivial

    mode = _solve_mode(solver)
    if getattr(solver, "needs_device_warmup", False):
        CONSTRAINT_DISPATCH_TOTAL.inc("kernel")
        rounds, unsched, chosen, group_level, overflow, _ = _dispatch_kernel(
            compiled, fleet, mode
        )
    else:
        CONSTRAINT_DISPATCH_TOTAL.inc("mirror")
        rounds, unsched, chosen, group_level, overflow, _ = _dispatch_mirror(
            compiled, fleet, mode
        )
    if overflow:
        # Static round budget exhausted — impossible by construction, but a
        # partial plan must never launch, and neither may an UNCONSTRAINED
        # greedy re-pack (it would drop the very masks/conflicts this solve
        # exists to enforce). The pods stay pending and heal through the
        # next sweep's fresh compile.
        return (
            ffd.PackResult(packings=[], unschedulable=list(schedule.pods)),
            trivial,
        )

    result = decode_constrained(rounds, unsched, compiled, chosen, fleet)
    dropped = _dropped_pods(compiled, groups, chosen)
    result.unschedulable.extend(dropped)
    dropped_uids = {pod.uid for pod in dropped}
    base_levels = [compiled.num_levels] * groups.num_groups
    for sub, level in enumerate(group_level):
        base = compiled.sub_base[sub]
        base_levels[base] = min(base_levels[base], int(level))
    decision = ConstraintDecision(
        chosen_level=chosen,
        group_levels=base_levels,
        pod_levels={
            pod.uid: chosen
            for pod in schedule.pods
            if pod.uid not in dropped_uids
        },
        description=compiled.ladder.describe(chosen),
    )
    CONSTRAINT_LEVEL_TOTAL.inc(str(chosen))
    return result, decision
