"""Control-plane error types."""


class PDBViolationError(Exception):
    """Eviction refused because it would violate a PodDisruptionBudget
    (ref: termination/eviction.go treats HTTP 429 as retryable)."""
