"""Counter: aggregate per-Provisioner node capacity into status.resources,
which Limits.exceeded_by consumes (ref: pkg/controllers/counter/controller.go).
"""

from __future__ import annotations

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.resources import add_resources
from karpenter_tpu.controllers.cluster import Cluster


class CounterController:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile(self, provisioner_name: str) -> None:
        provisioner = self.cluster.try_get_provisioner(provisioner_name)
        if provisioner is None:
            return
        nodes = self.cluster.list_nodes(
            predicate=lambda n: n.labels.get(wellknown.PROVISIONER_NAME_LABEL)
            == provisioner_name
            and n.deletion_timestamp is None
        )
        resources = add_resources(*[node.capacity for node in nodes])
        # Write-through only on change: a status write emits a watch event
        # which re-enqueues this reconcile — unconditional writes would spin.
        if resources != provisioner.status.resources:
            provisioner.status.resources = resources
            self.cluster.update_provisioner_status(provisioner)
