"""In-memory cluster state store — the envtest replacement.

Ref: pkg/test/environment.go boots a real apiserver via envtest; controllers
talk to it through a client. Here the same role is played by a thread-safe
in-process store with the handful of verbs the controllers use (get / list /
create / delete / bind / patch-like mutation under lock) plus watch-style
callbacks so the runtime can trigger reconciles on changes. All state the
framework needs survives in this store (SURVEY.md §5 checkpoint/resume: "all
state is in the Kubernetes API"); controllers stay stateless-restartable.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import Provisioner
from karpenter_tpu.cloudprovider import NodeSpec
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK
from karpenter_tpu.utils.fence import WriteFence

PodKey = Tuple[str, str]  # (namespace, name)


def reschedule_epoch(pod: PodSpec) -> int:
    """How many times this pod has been displaced back to pending (0 = never;
    see RESCHEDULE_EPOCH_ANNOTATION)."""
    raw = pod.annotations.get(wellknown.RESCHEDULE_EPOCH_ANNOTATION, "0")
    try:
        return int(raw)
    except ValueError:
        return 0


class NotFoundError(KeyError):
    pass


class AlreadyExistsError(Exception):
    """In-memory analogue of the apiserver's 409 AlreadyExists. Carries
    `status` so callers that branch on coded apiserver errors (e.g. the
    provisioning adopt-on-409 path) behave identically on both backends."""

    status = 409


class Cluster:
    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or SYSTEM_CLOCK
        self._lock = threading.RLock()
        self._pods: Dict[PodKey, PodSpec] = {}  # vet: guarded-by(self._lock)
        self._nodes: Dict[str, NodeSpec] = {}  # vet: guarded-by(self._lock)
        self._provisioners: Dict[str, Provisioner] = {}  # vet: guarded-by(self._lock)
        self._daemonsets: Dict[str, PodSpec] = {}  # vet: guarded-by(self._lock) — name -> pod template
        self._pdbs: Dict[str, Tuple[Dict[str, str], int]] = {}  # vet: guarded-by(self._lock) — selector, minAvailable
        self._leases: Dict[str, Tuple[str, float, int]] = {}  # vet: guarded-by(self._lock) — name -> (holder, expiry, transitions)
        self._watchers: List[Callable[[str, object], None]] = []
        self._delta_watchers: List[Callable[[str, str, object], None]] = []
        # Write fence: armed with the lease generation by the LeaderElector,
        # revoked the instant leadership is lost. Standalone in-memory this
        # store IS the shared state, so every origin write is fenced here;
        # the apiserver backend flips _fence_is_store off because this layer
        # is then only the informer cache — a deposed leader's watch pump
        # must keep syncing it — and moves the fence to the write-through
        # verbs (kubeapi/cluster.py).
        self.fence = WriteFence()
        self._fence_is_store = True

    def _fence_check(self, verb: str) -> None:
        if self._fence_is_store:
            self.fence.check(verb)

    # --- watch plumbing ----------------------------------------------------

    def watch(self, callback: Callable[[str, object], None]) -> None:
        """callback(kind, obj) on every mutation; kind in
        {pod, node, provisioner, daemonset}."""
        self._watchers.append(callback)

    def watch_deltas(self, callback: Callable[[str, str, object], None]) -> None:
        """callback(verb, kind, obj) on every mutation — the verb-level feed
        the incremental encoder consumes (models/cluster_state.py). Verbs:
        apply | bind | update | delete | reschedule. Delivery order across
        threads is NOT guaranteed; consumers must treat each event as a
        sync-this-key hint and re-read the store (which is always at least
        as new as the event), never as a replayable op log."""
        self._delta_watchers.append(callback)

    def _notify(self, kind: str, obj, verb: str = "apply") -> None:
        # INVARIANT (pinned by the blocking-under-lock vet rule): callback
        # dispatch runs OUTSIDE self._lock. Watch callbacks fan out into
        # reconcile enqueues and the incremental-encode sync, both of which
        # take their own locks — firing them under the store lock would
        # convoy every verb behind the slowest consumer and invite
        # lock-order inversions.
        for callback in list(self._watchers):
            callback(kind, obj)
        for callback in list(self._delta_watchers):
            callback(verb, kind, obj)

    # --- pods --------------------------------------------------------------

    def apply_pod(self, pod: PodSpec) -> PodSpec:
        self._fence_check("apply_pod")
        with self._lock:
            if pod.created_at is None:
                # Stamp creationTimestamp on first apply; an update arriving
                # without one (e.g. a watch-pump conversion) inherits the
                # stored pod's — the lifecycle tracker's restart re-anchor
                # depends on this surviving every round trip.
                existing = self._pods.get((pod.namespace, pod.name))
                pod.created_at = (
                    existing.created_at
                    if existing is not None and existing.created_at is not None
                    else self.clock.now()
                )
            self._pods[(pod.namespace, pod.name)] = pod
        self._notify("pod", pod)
        return pod

    def get_pod(self, namespace: str, name: str) -> PodSpec:
        with self._lock:
            try:
                return self._pods[(namespace, name)]
            except KeyError:
                raise NotFoundError(f"pod {namespace}/{name}")

    def try_get_pod(self, namespace: str, name: str) -> Optional[PodSpec]:
        # Lock-free: a single dict read is atomic under the GIL, and
        # mutators replace whole entries (never partially mutate the
        # mapping), so the read sees either the previous or the current
        # object — the same guarantee the lock gave a point read. This is
        # THE hottest read in a pod storm (one per selection reconcile),
        # and 128 selection workers convoyed on the cluster lock here.
        return self._pods.get((namespace, name))  # vet: unguarded(GIL-atomic point read; rationale above)

    def list_pods(
        self,
        node_name: Optional[str] = None,
        predicate: Optional[Callable[[PodSpec], bool]] = None,
    ) -> List[PodSpec]:
        """node_name uses the same role as the reference's spec.nodeName field
        index (ref: manager.go:60-66)."""
        with self._lock:
            pods = list(self._pods.values())
        if node_name is not None:
            pods = [p for p in pods if p.node_name == node_name]
        if predicate is not None:
            pods = [p for p in pods if predicate(p)]
        return pods

    def bind_pod(self, pod: PodSpec, node: NodeSpec) -> None:
        self._fence_check("bind_pod")
        with self._lock:
            stored = self._pods.get((pod.namespace, pod.name))
            if stored is None:
                raise NotFoundError(f"pod {pod.namespace}/{pod.name}")
            stored.node_name = node.name
            stored.unschedulable = False
        self._notify("pod", stored, verb="bind")

    def delete_pod(
        self, namespace: str, name: str, uid: Optional[str] = None
    ) -> bool:
        """uid, when given, preconditions the delete (DeleteOptions
        semantics): a same-name pod re-created since the caller observed the
        victim is left alone (compare-and-pop under the lock). Returns True
        iff this call removed the pod."""
        self._fence_check("delete_pod")
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                return False
            if uid and (getattr(pod, "uid", "") or "") != uid:
                return False
            self._pods.pop((namespace, name), None)
        self._notify("pod", pod, verb="delete")
        return True

    def evict_pod(self, namespace: str, name: str) -> None:
        """Eviction-API analogue: honors PDBs (429-equivalent refusal)
        (ref: termination/eviction.go:90-109)."""
        self._fence_check("evict_pod")
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                return
            if not self._pdb_allows(pod):
                from karpenter_tpu.controllers.errors import PDBViolationError

                raise PDBViolationError(f"pod {namespace}/{name} blocked by PDB")
            pod.deletion_timestamp = self.clock.now()
        self._notify("pod", pod, verb="update")

    def reschedule_pod(
        self, namespace: str, name: str, override_pdb: bool = False
    ) -> Optional[PodSpec]:
        """Displace a bound pod back to pending (node_name cleared,
        unschedulable set) so the provisioning path rebinds it onto fresh
        capacity — the interruption drain's replacement for evict-to-death
        (this store has no workload controller to re-create an evicted pod,
        so displacement IS the re-creation; see docs/design/interruption.md).
        The disruption is PDB-gated like eviction unless `override_pdb` (the
        deadline-escalation path, which prefers a budget violation over
        losing the pod uncleanly). Returns the displaced pod, or None when it
        no longer exists; a pod already unbound is returned unchanged."""
        self._fence_check("reschedule_pod")
        pod = self.try_get_pod(namespace, name)
        if pod is None or pod.node_name is None:
            return pod
        if not override_pdb and not self._pdb_allows(pod):
            from karpenter_tpu.controllers.errors import PDBViolationError

            raise PDBViolationError(f"pod {namespace}/{name} blocked by PDB")
        return self._reschedule_local(namespace, name)

    def _reschedule_local(self, namespace: str, name: str) -> Optional[PodSpec]:
        """The store-side half of reschedule_pod (the apiserver backend
        overrides this to write through first)."""
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                return None
            pod.node_name = None
            pod.unschedulable = True
            # The epoch bump makes the replacement a DIFFERENT logical launch
            # than the purchase backing the old node (the launch identity
            # hashes uid@epoch) — without it, a restart-idempotent provider
            # would adopt the dying instance and rebind the pod onto the very
            # node being reclaimed.
            pod.annotations[wellknown.RESCHEDULE_EPOCH_ANNOTATION] = str(
                reschedule_epoch(pod) + 1
            )
        self._notify("pod", pod, verb="reschedule")
        return pod

    # --- pod disruption budgets (simplified) --------------------------------

    def apply_pdb(self, name: str, match_labels: Dict[str, str], min_available: int):
        self._fence_check("apply_pdb")
        with self._lock:
            self._pdbs[name] = (dict(match_labels), min_available)

    def _pdb_allows(self, pod: PodSpec) -> bool:
        """Healthy = bound and not terminating: a pod displaced back to
        pending (reschedule_pod) is down for the whole relaunch+rebind
        latency, so it must not count toward the budget — otherwise one
        polite drain sweep could displace every replica behind a PDB, each
        step still seeing the previous victims as 'healthy'."""
        with self._lock:
            pdbs = list(self._pdbs.values())
        for match_labels, min_available in pdbs:
            if not all(pod.labels.get(k) == v for k, v in match_labels.items()):
                continue
            with self._lock:
                healthy = [
                    p
                    for p in self._pods.values()
                    if p.deletion_timestamp is None
                    and p.node_name is not None
                    and all(p.labels.get(k) == v for k, v in match_labels.items())
                ]
            # Disrupting an already-unhealthy pod costs the budget nothing.
            victim_counts = (
                pod.deletion_timestamp is None and pod.node_name is not None
            )
            if len(healthy) - (1 if victim_counts else 0) < min_available:
                return False
        return True

    # --- nodes -------------------------------------------------------------

    def create_node(self, node: NodeSpec) -> NodeSpec:
        """Strict create, like the apiserver: a duplicate name is a 409, not
        a silent overwrite — the provisioning adopt-on-409 path depends on
        creates failing loudly. Remote-sourced state (watch events) goes
        through `apply_node` instead."""
        self._fence_check("create_node")
        with self._lock:
            if node.name in self._nodes:
                raise AlreadyExistsError(f"node {node.name} already exists")
            if not node.created_at:
                node.created_at = self.clock.now()
            self._nodes[node.name] = node
        self._notify("node", node)
        return node

    def apply_node(self, node: NodeSpec) -> NodeSpec:
        """Upsert from an authoritative source (the kubeapi watch pump, a
        write-through whose create the real apiserver already admitted)."""
        with self._lock:
            if not node.created_at:
                node.created_at = self.clock.now()
            self._nodes[node.name] = node
        self._notify("node", node)
        return node

    def get_node(self, name: str) -> NodeSpec:
        with self._lock:
            try:
                return self._nodes[name]
            except KeyError:
                raise NotFoundError(f"node {name}")

    def try_get_node(self, name: str) -> Optional[NodeSpec]:
        # Lock-free point read — same GIL-atomicity argument as try_get_pod.
        return self._nodes.get(name)  # vet: unguarded(GIL-atomic point read; same argument as try_get_pod)

    def list_nodes(
        self, predicate: Optional[Callable[[NodeSpec], bool]] = None
    ) -> List[NodeSpec]:
        with self._lock:
            nodes = list(self._nodes.values())
        if predicate is not None:
            nodes = [n for n in nodes if predicate(n)]
        return nodes

    def update_node(self, node: NodeSpec) -> None:
        self._fence_check("update_node")
        self._notify("node", node, verb="update")

    def heartbeat_node(self, name: str, ready: bool = True) -> Optional[NodeSpec]:
        """Kubelet-side status report: stamp status_reported_at with the
        current clock and set the Ready condition. A dedicated verb (not
        update_node) because heartbeats are STATUS writes — the apiserver
        backend patches only status.conditions so a controller's concurrent
        metadata/spec patch is never clobbered, and vice versa. Unfenced:
        heartbeats come from the node's kubelet, not the (possibly deposed)
        controller leader. Returns the node, or None if it doesn't exist."""
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                return None
            node.ready = ready
            node.status_reported_at = self.clock.now()
        self._notify("node", node, verb="update")
        return node

    def remove_node_annotation(self, node: NodeSpec, key: str) -> None:
        """Delete one annotation. A dedicated verb because removal does NOT
        survive update_node on the apiserver backend: its merge-patch sends
        the annotations map, and RFC 7386 keeps server keys absent from the
        patch — the popped key would resurrect through the watch pump. The
        apiserver override patches the key to null explicitly."""
        self._fence_check("remove_node_annotation")
        with self._lock:
            node.annotations.pop(key, None)
        self._notify("node", node, verb="update")

    def delete_node(self, name: str) -> None:
        """Marks deletion; the object lingers while finalizers remain
        (ref: the apiserver finalizer protocol driving termination §3.4)."""
        self._fence_check("delete_node")
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                return
            if node.deletion_timestamp is None:
                node.deletion_timestamp = self.clock.now()
            removed = not node.finalizers
            if removed:
                self._nodes.pop(name, None)
        self._notify("node", node, verb="delete" if removed else "update")

    def remove_finalizer(self, node: NodeSpec, finalizer: str) -> None:
        self._fence_check("remove_finalizer")
        with self._lock:
            if finalizer in node.finalizers:
                node.finalizers.remove(finalizer)
            removed = node.deletion_timestamp is not None and not node.finalizers
            if removed:
                self._nodes.pop(node.name, None)
        self._notify("node", node, verb="delete" if removed else "update")

    # --- provisioners ------------------------------------------------------

    def apply_provisioner(self, provisioner: Provisioner) -> Provisioner:
        self._fence_check("apply_provisioner")
        with self._lock:
            self._provisioners[provisioner.name] = provisioner
        self._notify("provisioner", provisioner)
        return provisioner

    def try_get_provisioner(self, name: str) -> Optional[Provisioner]:
        # Lock-free point read — same GIL-atomicity argument as try_get_pod.
        return self._provisioners.get(name)  # vet: unguarded(GIL-atomic point read; same argument as try_get_pod)

    def list_provisioners(self) -> List[Provisioner]:
        # Copy under the lock, sort OUTSIDE it (the list_pods/list_nodes
        # pattern): the convoy on this path came from the O(n log n) sort
        # with its Python key lambda running under the shared lock, while
        # selection routes every reconcile through here. The copy itself is
        # not safely lock-free — list() allocation can trigger a GC pass
        # whose callbacks yield the GIL mid-materialization.
        with self._lock:
            provisioners = list(self._provisioners.values())
        return sorted(provisioners, key=lambda p: p.name)

    def update_provisioner_status(self, provisioner: Provisioner) -> None:
        """Persist a status mutation (resources/conditions/lastScaleTime).
        In-memory the object IS the store so this only notifies; the
        apiserver backend PATCHes the CRD status subresource — controllers
        must route status writes through here to survive either backend."""
        self._fence_check("update_provisioner_status")
        self._notify("provisioner", provisioner)

    def delete_provisioner(self, name: str) -> None:
        self._fence_check("delete_provisioner")
        with self._lock:
            provisioner = self._provisioners.pop(name, None)
        if provisioner is not None:
            provisioner.deletion_timestamp = self.clock.now()
            self._notify("provisioner", provisioner, verb="delete")

    # --- daemonsets ---------------------------------------------------------

    def apply_daemonset(self, name: str, pod_template: PodSpec) -> None:
        self._fence_check("apply_daemonset")
        with self._lock:
            self._daemonsets[name] = pod_template
        self._notify("daemonset", pod_template)

    def list_daemonset_templates(self) -> List[PodSpec]:
        with self._lock:
            return list(self._daemonsets.values())

    # --- leases (coordination.k8s.io Lease analogue) -----------------------

    def acquire_lease(
        self,
        name: str,
        holder: str,
        duration_s: float,
        *,
        transitions: Optional[int] = None,
    ) -> int:
        """Compare-and-swap acquire/renew: succeeds when the lease is free,
        expired, or already held by `holder` (renewal). The store-side
        analogue of the Lease object the reference's leader election uses
        (ref: cmd/controller/main.go:80-81).

        Returns the lease's ``transitions`` counter (>= 1) on success and 0
        on a lost CAS, so callers keep their old truthiness checks while the
        elector learns its generation. The counter bumps only on a holder
        CHANGE (kube leaseTransitions semantics): renewals — and a holder
        re-acquiring its own expired or committed-then-lost lease — keep the
        prior value, which is what makes the generation a fencing token: it
        moves exactly when writes may have interleaved with a rival's.

        ``transitions`` (keyword-only) lets the apiserver backend mirror the
        SERVER's committed counter into this cache instead of recomputing it
        locally — the mirror must never drift from the store of record.
        """
        with self._lock:
            now = self.clock.now()
            current = self._leases.get(name)
            prior_holder: Optional[str] = None
            prior_transitions = 0
            if current is not None:
                prior_holder, expiry, prior_transitions = current
                if prior_holder != holder and now < expiry:
                    return 0
            if transitions is not None:
                committed = int(transitions)
            elif prior_holder == holder:
                committed = prior_transitions
            else:
                committed = prior_transitions + 1
            self._leases[name] = (holder, now + duration_s, committed)
            return committed

    def release_lease(self, name: str, holder: str) -> bool:
        with self._lock:
            current = self._leases.get(name)
            if current is None or current[0] != holder:
                return False
            # Keep the transitions counter under the tombstoned name so the
            # next holder still observes a bump — dropping it would reissue
            # generation 1 and alias the first holder's fence token.
            _, _, prior_transitions = current
            self._leases[name] = ("", 0.0, prior_transitions)
            return True

    def get_lease(self, name: str) -> Optional[Tuple[str, float, int]]:
        """(holder, expiry, transitions) or None; expired or released leases
        read as None."""
        with self._lock:
            current = self._leases.get(name)
            if current is None or not current[0] or self.clock.now() >= current[1]:
                return None
            return current
