"""Metrics controller: periodic gauges for capacity and pod phases.

Ref: pkg/controllers/metrics/{controller,nodes,pods}.go — polls every 10s per
Provisioner and publishes node counts by {provisioner}×{zone|arch|instance
-type} plus pod-phase counts.
"""

from __future__ import annotations

from collections import Counter

from karpenter_tpu.api import wellknown
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.utils.metrics import REGISTRY

POLL_SECONDS = 10.0  # ref: metrics/controller.go:69

NODE_COUNT_BY_ZONE = REGISTRY.gauge(
    "nodes_by_zone", "Node count per provisioner and zone", ["provisioner", "zone"]
)
NODE_COUNT_BY_ARCH = REGISTRY.gauge(
    "nodes_by_arch", "Node count per provisioner and architecture", ["provisioner", "arch"]
)
NODE_COUNT_BY_INSTANCE_TYPE = REGISTRY.gauge(
    "nodes_by_instance_type",
    "Node count per provisioner and instance type",
    ["provisioner", "instance_type"],
)
POD_COUNT_BY_PHASE = REGISTRY.gauge(
    "pods_by_phase", "Pod count per provisioner and phase", ["provisioner", "phase"]
)

# Ready-vs-total split (ref: metrics/nodes.go:33-96 — capacity_node_count by
# provisioner plus ready_node_* splits by zone/arch/instance-type/OS).
NODE_COUNT = REGISTRY.gauge(
    "capacity_node_count", "Total node count by provisioner", ["provisioner"]
)
READY_NODE_COUNT = REGISTRY.gauge(
    "capacity_ready_node_count",
    "Count of ready nodes by provisioner and zone",
    ["provisioner", "zone"],
)
READY_NODE_COUNT_BY_ARCH = REGISTRY.gauge(
    "capacity_ready_node_arch_count",
    "Count of ready nodes by architecture, provisioner, and zone",
    ["arch", "provisioner", "zone"],
)
READY_NODE_COUNT_BY_INSTANCE_TYPE = REGISTRY.gauge(
    "capacity_ready_node_instancetype_count",
    "Count of ready nodes by instance type, provisioner, and zone",
    ["instance_type", "provisioner", "zone"],
)
READY_NODE_COUNT_BY_OS = REGISTRY.gauge(
    "capacity_ready_node_os_count",
    "Count of ready nodes by operating system, provisioner, and zone",
    ["os", "provisioner", "zone"],
)


class MetricsController:
    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def reconcile(self, provisioner_name: str) -> float:
        # Clear this provisioner's series first so vanished zones/types/phases
        # don't keep reporting their last value forever. The provisioner label
        # is first on the by-provisioner gauges, second on the ready splits
        # whose leading label is the breakdown dimension (matching the
        # reference's label order, nodes.go:55-96).
        for gauge in (
            NODE_COUNT_BY_ZONE,
            NODE_COUNT_BY_ARCH,
            NODE_COUNT_BY_INSTANCE_TYPE,
            POD_COUNT_BY_PHASE,
            NODE_COUNT,
            READY_NODE_COUNT,
        ):
            gauge.remove_where(lambda key: key and key[0] == provisioner_name)
        for gauge in (
            READY_NODE_COUNT_BY_ARCH,
            READY_NODE_COUNT_BY_INSTANCE_TYPE,
            READY_NODE_COUNT_BY_OS,
        ):
            gauge.remove_where(
                lambda key: len(key) > 1 and key[1] == provisioner_name
            )
        nodes = self.cluster.list_nodes(
            predicate=lambda n: n.labels.get(wellknown.PROVISIONER_NAME_LABEL)
            == provisioner_name
        )
        by_zone: Counter = Counter(n.zone for n in nodes if n.zone)
        by_arch: Counter = Counter(
            n.labels.get(wellknown.ARCH_LABEL, "") for n in nodes
        )
        by_type: Counter = Counter(n.instance_type for n in nodes if n.instance_type)
        for zone, count in by_zone.items():
            NODE_COUNT_BY_ZONE.set(count, provisioner_name, zone)
        for arch, count in by_arch.items():
            if arch:
                NODE_COUNT_BY_ARCH.set(count, provisioner_name, arch)
        for instance_type, count in by_type.items():
            NODE_COUNT_BY_INSTANCE_TYPE.set(count, provisioner_name, instance_type)

        # Ready-vs-total split (ref: nodes.go publishNodeCounts).
        NODE_COUNT.set(len(nodes), provisioner_name)
        ready = [n for n in nodes if n.ready]
        ready_by_zone: Counter = Counter(n.zone for n in ready if n.zone)
        for zone, count in ready_by_zone.items():
            READY_NODE_COUNT.set(count, provisioner_name, zone)
        ready_arch: Counter = Counter(
            (n.labels.get(wellknown.ARCH_LABEL, ""), n.zone) for n in ready if n.zone
        )
        for (arch, zone), count in ready_arch.items():
            if arch:
                READY_NODE_COUNT_BY_ARCH.set(count, arch, provisioner_name, zone)
        ready_type: Counter = Counter(
            (n.instance_type, n.zone) for n in ready if n.zone and n.instance_type
        )
        for (instance_type, zone), count in ready_type.items():
            READY_NODE_COUNT_BY_INSTANCE_TYPE.set(
                count, instance_type, provisioner_name, zone
            )
        ready_os: Counter = Counter(
            (n.labels.get(wellknown.OS_LABEL, ""), n.zone) for n in ready if n.zone
        )
        for (os_name, zone), count in ready_os.items():
            if os_name:
                READY_NODE_COUNT_BY_OS.set(count, os_name, provisioner_name, zone)

        node_names = {n.name for n in nodes}
        phases: Counter = Counter(
            p.phase for p in self.cluster.list_pods() if p.node_name in node_names
        )
        for phase, count in phases.items():
            POD_COUNT_BY_PHASE.set(count, provisioner_name, phase)
        return POLL_SECONDS
