"""Unhealthy-node detection and the escalation ladder.

The Liveness guard (controllers/node.py) only covers nodes that NEVER
joined — a kubelet that reported once and then went dark left an immortal
NotReady node that kept receiving pods. This controller closes that gap:

1. **Detection with hysteresis.** A managed, joined node is unhealthy when
   its heartbeat is stale (``status_reported_at`` older than
   ``--node-unreachable-timeout``) or its kubelet reports NotReady. One bad
   observation proves nothing — watch delivery jitters and kubelets flap —
   so escalation waits for ``STALE_OBSERVATIONS`` consecutive unhealthy
   sweeps. A single fresh heartbeat resets the counter.

2. **The escalation ladder** (the same drain machinery interruption and
   consolidation already ride): re-taint ``karpenter.sh/not-ready`` →
   cordon → PDB-gated displacement via ``reschedule_pod`` (the
   reschedule-epoch bump makes every replacement a DIFFERENT logical launch,
   so a restart-idempotent provider can never adopt the dying node's
   purchase) → displaced pods fed straight to ``ProvisionerWorker.add`` so
   replacement capacity launches while the drain runs → finalizer-path
   delete (termination drains the daemon tail and calls the cloud delete).

3. **Stuck-drain breaker.** A polite drain blocked past
   ``--drain-stuck-timeout`` (do-not-evict pods, PDB refusals, an eviction
   black-hole) escalates loudly — overrides are taken and counted on
   ``drain_stalled_total{reason="unreachable"}`` — because leaving pods on
   an unreachable node is strictly worse than any budget.

4. **Zombie defense.** A deleted node's kubelet re-registering under the
   same name must not be adopted: a re-registration carrying the DEAD
   incarnation's provider id is rejected (the launch-identity analogue — a
   legitimate replacement always rides a fresh launch, hence a fresh
   provider id), and a node whose instance no provider listing accounts for
   (two consecutive sightings, the instancegc pattern) is reaped the same
   way. Both count ``node_zombie_rejections_total``.

Crash consistency: ``health.after-cordon`` / ``health.mid-displace`` are
named crashpoints; the battletest (tests/test_health.py, `make
lifecycle-smoke`) kills the controller at each and asserts a restart
converges with every pod rebound exactly once.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.taints import Taint
from karpenter_tpu.cloudprovider import CloudProvider, NodeSpec
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.errors import PDBViolationError
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.termination import (
    DRAIN_STALLED_TOTAL,
    TerminationController,
)
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.crashpoints import crashpoint
from karpenter_tpu.utils.metrics import REGISTRY

SWEEP_SECONDS = 2.0
# Heartbeat age past which a joined node counts as unreachable
# (--node-unreachable-timeout; kube's node-monitor-grace-period analogue).
DEFAULT_UNREACHABLE_TIMEOUT = 60.0
# Polite-drain budget once a node is confirmed unhealthy; past it the drain
# overrides do-not-evict and PDBs rather than leaving pods on a dead node
# (--drain-stuck-timeout).
DEFAULT_DRAIN_STUCK_TIMEOUT = 120.0
# Consecutive unhealthy sweeps before the ladder engages — the flap
# hysteresis. One fresh heartbeat resets the count.
STALE_OBSERVATIONS = 3

NODE_UNHEALTHY_TOTAL = REGISTRY.counter(
    "node_unhealthy_total",
    "Nodes confirmed unhealthy (hysteresis passed) and escalated, by reason",
    ["reason"],
)
NODE_HEARTBEAT_STALE_SECONDS = REGISTRY.gauge(
    "node_heartbeat_stale_seconds",
    "Worst heartbeat staleness across joined, managed, live nodes",
)
NODE_ZOMBIE_REJECTIONS_TOTAL = REGISTRY.counter(
    "node_zombie_rejections_total",
    "Re-registrations of deleted nodes (or instance-less ghosts) rejected",
)


class HealthController:
    """Periodic sweep (Manager drives it like interruption): detect stale or
    NotReady nodes, escalate through cordon→displace→replace→delete."""

    def __init__(
        self,
        cluster: Cluster,
        cloud: CloudProvider,
        provisioning: ProvisioningController,
        termination: TerminationController,
        unreachable_timeout: float = DEFAULT_UNREACHABLE_TIMEOUT,
        drain_stuck_timeout: float = DEFAULT_DRAIN_STUCK_TIMEOUT,
        stale_observations: int = STALE_OBSERVATIONS,
        cluster_state=None,
    ):
        self.cluster = cluster
        self.cloud = cloud
        self.provisioning = provisioning
        self.termination = termination
        self.unreachable_timeout = unreachable_timeout
        self.drain_stuck_timeout = drain_stuck_timeout
        self.stale_observations = stale_observations
        # Incremental encoder (optional): per-node pod listing without an
        # O(pods) filter per node per sweep, same as interruption.
        self.cluster_state = cluster_state
        self.log = klog.named("health")
        # node name -> consecutive unhealthy sweeps. In-memory: a restart
        # re-counts from zero, which only DELAYS escalation by K sweeps —
        # never acts on less evidence than the configured hysteresis.
        self._strikes: Dict[str, int] = {}
        # node name -> clock time escalation engaged (hysteresis passed);
        # the drain-stuck anchor. Doubles as the "already counted" marker
        # so node_unhealthy_total counts episodes, not sweeps.
        self._unhealthy_since: Dict[str, float] = {}
        # Nodes whose stall already fired drain_stalled_total this episode.
        self._stalled: set = set()
        # name -> provider_id of nodes THIS controller deleted: the zombie
        # check's fast path. In-memory and bounded; the instance-less ghost
        # sweep below is the restart-durable layer.
        self._buried: Dict[str, str] = {}
        # provider_id -> first sighting for the instance-less ghost check
        # (two consecutive sightings, the instancegc pattern).
        self._ghost_suspects: Dict[str, float] = {}

    # --- sweep --------------------------------------------------------------

    def reconcile(self, _key=None) -> float:
        now = self.cluster.clock.now()
        managed = [
            node
            for node in self.cluster.list_nodes()
            if wellknown.PROVISIONER_NAME_LABEL in node.labels
            and node.deletion_timestamp is None
        ]
        self._reject_zombies(managed, now)
        unhealthy = self._classify(managed, now)
        # Prune bookkeeping for nodes that left the unhealthy set entirely —
        # including ones deleted between sweeps, which the loop never visits.
        names = {node.name for node, _ in unhealthy}
        for name in list(self._strikes):
            if name not in names:
                self._forget(name)
        for node, reason in unhealthy:
            strikes = self._strikes.get(node.name, 0) + 1
            self._strikes[node.name] = strikes
            if strikes < self.stale_observations:
                continue  # hysteresis: flaps don't reach the ladder
            if node.name not in self._unhealthy_since:
                self._unhealthy_since[node.name] = now
                NODE_UNHEALTHY_TOTAL.inc(reason)
                self.log.warning(
                    "node %s unhealthy (%s) after %d consecutive "
                    "observations; escalating",
                    node.name, reason, strikes,
                )
            self._escalate(node, now)
        return SWEEP_SECONDS

    def _classify(self, managed: List[NodeSpec], now: float) -> List[tuple]:
        """Split the managed fleet into healthy (strikes forgotten) and
        (node, reason) suspects, publishing the worst-staleness gauge."""
        unhealthy: List[tuple] = []
        worst_staleness = 0.0
        for node in managed:
            if node.status_reported_at is None:
                continue  # never joined: the Liveness guard's case
            if wellknown.INTERRUPTION_KIND_ANNOTATION in node.annotations:
                continue  # the interruption drain already owns this node
            staleness = now - node.status_reported_at
            worst_staleness = max(worst_staleness, staleness)
            stale = staleness >= self.unreachable_timeout
            if not stale and node.ready:
                self._forget(node.name)
                continue
            reason = "stale-heartbeat" if stale else "not-ready"
            unhealthy.append((node, reason))
        NODE_HEARTBEAT_STALE_SECONDS.set(worst_staleness)
        return unhealthy

    def _forget(self, name: str) -> None:
        self._strikes.pop(name, None)
        self._unhealthy_since.pop(name, None)
        self._stalled.discard(name)

    # --- zombie defense -----------------------------------------------------

    def _reject_zombies(self, managed: List[NodeSpec], now: float) -> None:
        """Reject re-registrations of dead nodes. Fast path: a node carrying
        a provider id this controller already buried is its old kubelet
        phoning home, not a replacement (replacements ride fresh launches =
        fresh provider ids). Durable path: a node whose instance the
        provider listing cannot account for on two consecutive sightings is
        a ghost — survives controller restarts because it reads only
        cloud + store state. Skipped when the provider enumerates nothing
        at all (a backend without list_instances must not nuke the fleet)."""
        instances = {
            instance.provider_id for instance in self.cloud.list_instances()
        }
        suspects: Dict[str, float] = {}
        for node in managed:
            if not node.provider_id:
                continue  # manually-registered test nodes: not ours to judge
            buried = self._buried.get(node.name)
            if buried is not None and node.provider_id == buried:
                self._reject(node, "re-registration of deleted node")
                continue
            if not instances or node.provider_id in instances:
                continue
            first_seen = self._ghost_suspects.get(node.provider_id)
            if first_seen is None:
                suspects[node.provider_id] = now  # wait one sweep
                continue
            suspects[node.provider_id] = first_seen
            self._reject(node, "no backing instance")
        self._ghost_suspects = suspects

    def _reject(self, node: NodeSpec, why: str) -> None:
        NODE_ZOMBIE_REJECTIONS_TOTAL.inc()
        self.log.warning(
            "rejecting zombie node %s (%s): %s",
            node.name, node.provider_id, why,
        )
        self.termination.terminator.cordon(node)
        self.cluster.delete_node(node.name)

    # --- escalation ladder ----------------------------------------------------

    def _escalate(self, node: NodeSpec, now: float) -> None:
        # Re-taint first: the solver must stop packing onto the sick node
        # even while the (possibly slow) drain runs. Idempotent — Readiness
        # re-adds it too once node.ready goes false, but a gone-dark kubelet
        # never flips the flag itself.
        if not any(t.key == wellknown.NOT_READY_TAINT_KEY for t in node.taints):
            node.taints.append(
                Taint(key=wellknown.NOT_READY_TAINT_KEY, effect="NoSchedule")
            )
            self.cluster.update_node(node)
        self.termination.terminator.cordon(node)
        crashpoint("health.after-cordon")
        anchor = self._unhealthy_since.get(node.name, now)
        escalated = now - anchor >= self.drain_stuck_timeout
        if escalated and node.name not in self._stalled:
            # The stuck-drain breaker: same loud shape as interruption's
            # deadline override, counted on the shared drain-stall counter.
            self._stalled.add(node.name)
            DRAIN_STALLED_TOTAL.inc("unreachable")
            self.log.warning(
                "drain of unhealthy node %s stuck for %.0fs; escalating "
                "over PDBs and do-not-evict",
                node.name, now - anchor,
            )
        displaced = [
            self._displace(node, pod, escalated)
            for pod in self._replaceable(node)
        ]
        if not all(displaced):
            return  # protected/PDB-blocked pods wait for the next sweep
        # Drained of everything replaceable: the finalizer path takes over
        # (termination drains the daemon tail, deletes at the cloud, strips
        # the finalizer) — instancegc invariants hold unchanged. Bury the
        # provider id so the dead kubelet re-registering is rejected.
        if node.provider_id:
            if len(self._buried) >= 4096:
                self._buried.clear()  # bounded; the ghost sweep still covers
            self._buried[node.name] = node.provider_id
        self._forget(node.name)
        self.cluster.delete_node(node.name)
        self.log.info("unhealthy node %s drained; deleting", node.name)

    def _replaceable(self, node: NodeSpec) -> List[PodSpec]:
        """Pods worth replacement capacity — the same drain-eligibility
        predicate the terminator uses, so the handoff can't disagree."""
        if self.cluster_state is not None:
            pods = self.cluster_state.pods_on_node(node.name)
        else:
            pods = self.cluster.list_pods(node_name=node.name)
        return [pod for pod in pods if pod.survives_node_drain()]

    def _displace(self, node: NodeSpec, pod: PodSpec, escalated: bool) -> bool:
        """Unbind one pod back to pending and feed it to the provisioner.
        Polite before the stuck-drain deadline; past it, overrides are taken
        (and counted) rather than leaving the pod on an unreachable node."""
        protected = wellknown.DO_NOT_EVICT_ANNOTATION in pod.annotations
        if protected and not escalated:
            return False
        try:
            live = self.cluster.reschedule_pod(pod.namespace, pod.name)
        except PDBViolationError:
            if not escalated:
                return False
            live = self.cluster.reschedule_pod(
                pod.namespace, pod.name, override_pdb=True
            )
            self.log.warning(
                "stuck-drain escalation: displacing %s/%s from %s OVER its PDB",
                pod.namespace, pod.name, node.name,
            )
        if live is None:
            return True  # vanished under us: nothing left to replace
        if protected:
            self.log.warning(
                "stuck-drain escalation: displacing %s/%s from %s despite "
                "do-not-evict", pod.namespace, pod.name, node.name,
            )
        crashpoint("health.mid-displace")
        self._feed(node, live)
        return True

    def _feed(self, node: NodeSpec, pod: PodSpec) -> None:
        """Proactive replacement: hand the displaced pod straight to the
        owning provisioner's batch window so replacement capacity launches
        while the rest of the drain runs. Without a worker the reschedule's
        watch event still routes the pod through selection."""
        name = node.labels.get(wellknown.PROVISIONER_NAME_LABEL, "")
        worker = self.provisioning.worker(name)
        if worker is not None:
            worker.add(pod)
