"""Drift detection and budgeted rolling replacement.

A Provisioner's spec is a statement about what its nodes SHOULD look like;
nothing before this controller ever re-checked running capacity against it.
Flip a taint, move an AMI, drop an instance type from the catalog — and the
old fleet keeps running the old answer forever (the reference calls this
drift; ref: the machine-drift controller in modern Karpenter). This sweep
closes that loop with three drift kinds, all rolled through ONE budgeted,
strictly-voluntary replacement path:

1. **Spec-hash drift** (kind ``spec``). Every node is stamped at
   registration with `karpenter.sh/provisioner-hash` — the canonical hash
   (karpenter_tpu/drift) of the STORED constraint envelope that launched
   it. The sweep recomputes the hash from the current stored spec; a
   mismatch means the operator changed the envelope and this node predates
   the change. A MISSING hash is never drift: legacy/adopted nodes are
   stamped with the current hash on sight (here and by the node
   controller's HashStamp) and participate from the next change onward.

2. **Provider-side drift** (kind ``provider``). `CloudProvider.
   instance_drifted(node)` — launch-template/AMI generation moved, the
   instance type vanished from the raw catalog, or the node's spot pool has
   been ICE-closed past a sustained window. The provider returns a human
   reason string; any non-None answer nominates the node.

3. **Expiration** (kind ``expired``). `ttlSecondsUntilExpired` elapsed —
   previously its own sub-reconciler deleting unconditionally, now just
   another drift kind riding the same budget (controllers/node.py's
   Expiration claims through the same ledger, so whichever actor sees the
   expired node first wins and the other never double-claims).

Replacement follows the consolidation drain discipline: durable
DRIFT_ACTION annotation FIRST (the restart-resume record and the ledger's
in-flight marker), cordon, PDB-gated `reschedule_pod` displacement with the
epoch bump, displaced pods fed straight to the owning provisioner's batch
window — replacement capacity is launching BEFORE the victim finishes
draining — then the finalizer-path delete. Strictly voluntary: PDB refusals
roll to the next sweep, a do-not-evict pod cancels the action, and
interruption-claimed or deleting nodes are never touched.

The sweep claims at most `DisruptionLedger.headroom("drift")` new victims
per pass — min(global `--disruption-budget` remaining, `--drift-max-
disruption` remaining) — so a spec flip over a 50-node fleet rolls
budget-at-a-time instead of draining everything at once.

Crash consistency: `drift.{after-mark,mid-replace,before-delete}` are named
crashpoints; tests/test_drift.py kills the controller at each and asserts a
restart converges from the durable annotation — pods bound exactly once,
victim gone, zero leaks.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from karpenter_tpu import drift as driftlib
from karpenter_tpu.api import wellknown
from karpenter_tpu.cloudprovider import CloudProvider, NodeSpec
from karpenter_tpu.controllers import eligibility
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.errors import PDBViolationError
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.termination import TerminationController
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.crashpoints import crashpoint
from karpenter_tpu.utils.metrics import REGISTRY
from karpenter_tpu.utils.obs import RECORDER

SWEEP_SECONDS = 30.0

DRIFT_NODES = REGISTRY.gauge(
    "drift_nodes",
    "Live nodes currently detected as drifted, by reason "
    "(spec|provider|expired), as of the last sweep — includes nodes the "
    "budget hasn't reached yet",
    ["reason"],
)
DRIFT_REPLACEMENTS_TOTAL = REGISTRY.counter(
    "drift_replacements_total",
    "Drift replacement outcomes by drift kind and result "
    "(executed|blocked|cancelled)",
    ["kind", "result"],
)


class DriftController:
    """Periodic sweep (Manager drives it like consolidation): detect
    drifted nodes, claim up to the shared budget, roll each through the
    annotate->cordon->displace->delete replacement path."""

    def __init__(
        self,
        cluster: Cluster,
        cloud: CloudProvider,
        provisioning: ProvisioningController,
        termination: TerminationController,
        ledger: Optional[eligibility.DisruptionLedger] = None,
        enabled: bool = True,
    ):
        self.cluster = cluster
        self.cloud = cloud
        self.provisioning = provisioning
        self.termination = termination
        self.enabled = enabled
        self.ledger = ledger or eligibility.DisruptionLedger(cluster)
        self.log = klog.named("drift")

    # --- sweep --------------------------------------------------------------

    def reconcile(self, _key=None) -> float:
        if not self.enabled:
            return SWEEP_SECONDS
        # Resume in-flight replacements first: the durable annotation is the
        # restart-resume record, exactly like consolidation's.
        for node in self.cluster.list_nodes():
            if (
                wellknown.DRIFT_ACTION_ANNOTATION in node.annotations
                and node.deletion_timestamp is None
            ):
                self._drain(node)
        drifted = self._detect()
        counts = {kind: 0 for kind in driftlib.DRIFT_KINDS}
        for _, kind, _ in drifted:
            counts[kind] += 1
        for kind in driftlib.DRIFT_KINDS:
            DRIFT_NODES.set(float(counts[kind]), kind)
        budget = self.ledger.headroom(eligibility.REASON_DRIFT)
        for node, kind, reason in drifted[:budget]:
            self._begin(node, kind, reason)
        return SWEEP_SECONDS

    def _detect(self) -> List[Tuple[NodeSpec, str, str]]:
        """Every un-claimed drifted node as (node, kind, reason), oldest
        first — a rolling upgrade replaces the stalest capacity first and
        the order is deterministic under equal ages (name tie-break)."""
        drifted: List[Tuple[NodeSpec, str, str]] = []
        for node in sorted(
            self.cluster.list_nodes(), key=lambda n: (n.created_at, n.name)
        ):
            provisioner_name = node.labels.get(wellknown.PROVISIONER_NAME_LABEL)
            if provisioner_name is None:
                continue  # not ours
            provisioner = self.cluster.try_get_provisioner(provisioner_name)
            if provisioner is None:
                continue
            if not eligibility.voluntary_disruption_allowed(node):
                continue
            if eligibility.claim_reason(node) is not None:
                continue  # already in flight (ours or another actor's)
            verdict = self._drift_verdict(provisioner, node)
            if verdict is not None:
                drifted.append((node, verdict[0], verdict[1]))
        return drifted

    def _drift_verdict(self, provisioner, node: NodeSpec) -> Optional[Tuple[str, str]]:
        """(kind, reason) when the node is drifted, else None. The spec hash
        is checked first (the cheapest and most common), then expiration,
        then the provider round-trip (potentially an API call per node)."""
        current = driftlib.spec_hash(provisioner)
        stamped = node.annotations.get(wellknown.PROVISIONER_HASH_ANNOTATION)
        if stamped is None:
            # Never drift while unstamped: adopt the node into the CURRENT
            # generation (see module docstring).
            node.annotations[wellknown.PROVISIONER_HASH_ANNOTATION] = current
            self.cluster.update_node(node)
            return None
        if stamped != current:
            return (
                driftlib.DRIFT_KIND_SPEC,
                f"provisioner hash {stamped} != current {current}",
            )
        ttl = provisioner.spec.ttl_seconds_until_expired
        if ttl is not None:
            age = self.cluster.clock.now() - node.created_at
            if age >= ttl:
                return (
                    driftlib.DRIFT_KIND_EXPIRED,
                    f"node age {age:.0f}s >= ttlSecondsUntilExpired {ttl}s",
                )
        try:
            provider_reason = self.cloud.instance_drifted(node)
        except Exception:  # noqa: BLE001 — drift is voluntary; API trouble = not drifted
            provider_reason = None
        if provider_reason is not None:
            return (driftlib.DRIFT_KIND_PROVIDER, provider_reason)
        return None

    # --- execution -----------------------------------------------------------

    def _begin(self, node: NodeSpec, kind: str, reason: str) -> None:
        live = self.cluster.try_get_node(node.name)
        if (
            live is None
            or not eligibility.voluntary_disruption_allowed(live)
            or eligibility.claim_reason(live) is not None
        ):
            return  # the cluster moved under the sweep: drop the nomination
        # Durable intent FIRST: a controller that dies past this point
        # resumes the replacement from the annotation.
        live.annotations[wellknown.DRIFT_ACTION_ANNOTATION] = kind
        self.cluster.update_node(live)
        RECORDER.record(
            "drift",
            node=live.name,
            drift_kind=kind,
            reason=reason,
            instance_type=live.instance_type,
        )
        self.log.info(
            "drift (%s) on %s (%s %s/%s): %s — beginning rolling replacement",
            kind, live.name, live.instance_type, live.zone,
            live.capacity_type, reason,
        )
        crashpoint("drift.after-mark")
        displaced = self._drain(live)
        if displaced == 0 and self.cluster.try_get_node(live.name) is not None:
            DRIFT_REPLACEMENTS_TOTAL.inc(kind, "blocked")

    def _drain(self, node: NodeSpec) -> Optional[int]:
        """One polite drain pass; returns how many pods were displaced, or
        None when the action was CANCELLED. Completes with the finalizer-
        path delete once nothing replaceable remains."""
        pods = [
            p
            for p in self.cluster.list_pods(node_name=node.name)
            if p.survives_node_drain()
        ]
        if any(
            wellknown.DO_NOT_EVICT_ANNOTATION in p.annotations for p in pods
        ):
            # A protection appeared after nomination: drift replacement is
            # voluntary, so the action is cancelled, not escalated. The node
            # stays drifted and re-nominates once the protection lifts.
            self._cancel(node)
            return None
        self.termination.terminator.cordon(node)
        displaced = 0
        for pod in pods:
            try:
                live = self.cluster.reschedule_pod(pod.namespace, pod.name)
            except PDBViolationError:
                continue  # budget spent: the drain rolls, one sweep at a time
            if live is None:
                continue  # vanished under us
            displaced += 1
            crashpoint("drift.mid-replace")
            # Replacement ahead of the drain: the displaced pod goes straight
            # to the owning provisioner's batch window, so fresh capacity —
            # carrying the CURRENT spec hash — is launching while the rest of
            # the victim drains.
            self._feed(node, live)
        remaining = [
            p
            for p in self.cluster.list_pods(node_name=node.name)
            if p.survives_node_drain()
        ]
        if not remaining:
            self._complete(node)
        return displaced

    def _complete(self, node: NodeSpec) -> None:
        crashpoint("drift.before-delete")
        kind = node.annotations.get(
            wellknown.DRIFT_ACTION_ANNOTATION, driftlib.DRIFT_KIND_SPEC
        )
        DRIFT_REPLACEMENTS_TOTAL.inc(kind, "executed")
        self.cluster.delete_node(node.name)
        self.log.info("drifted node %s drained; deleting (%s)", node.name, kind)

    def _cancel(self, node: NodeSpec) -> None:
        kind = node.annotations.get(
            wellknown.DRIFT_ACTION_ANNOTATION, driftlib.DRIFT_KIND_SPEC
        )
        # The dedicated removal verb: a plain update_node merge-patch cannot
        # delete the key on the apiserver backend, and a resurrected claim
        # would consume the disruption budget forever.
        self.cluster.remove_node_annotation(
            node, wellknown.DRIFT_ACTION_ANNOTATION
        )
        if (
            node.deletion_timestamp is None
            and wellknown.INTERRUPTION_KIND_ANNOTATION not in node.annotations
        ):
            node.unschedulable = False  # undo our cordon
        self.cluster.update_node(node)
        DRIFT_REPLACEMENTS_TOTAL.inc(kind, "cancelled")
        self.log.warning(
            "drift replacement of %s cancelled: a do-not-evict pod appeared "
            "mid-drain (voluntary disruption never overrides protections)",
            node.name,
        )

    def _feed(self, node: NodeSpec, pod) -> None:
        name = node.labels.get(wellknown.PROVISIONER_NAME_LABEL, "")
        worker = self.provisioning.worker(name)
        if worker is not None:
            worker.add(pod)
