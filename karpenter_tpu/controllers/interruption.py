"""Interruption-aware capacity reclamation.

The provider buys spot deliberately (cloudprovider/market.py prices the
discount) but a reclaim notice used to go unanswered: the node died under its
pods, selection re-discovered them as unschedulable, and users ate the full
re-provision latency. The reference grew an interruption controller consuming
the EC2 spot-interruption-warning / rebalance-recommendation /
instance-state-change streams precisely because reacting inside the 2-minute
window is the difference between "uses spot" and "survives spot". This is
that subsystem:

1. **Ingest (record-then-ack).** `CloudProvider.poll_interruptions()` is
   at-least-once: each event is stamped onto the victim Node as annotations
   (`karpenter.sh/interruption-{kind,deadline}`) — the durable intent a
   restarted controller resumes from — and only then acked. The interrupted
   (type, zone, capacity-type) pool is fed to the provider's offering
   blackout so replacement capacity re-solves AWAY from the pool being
   reclaimed.

2. **Deadline-driven drain.** The node is cordoned immediately. Replaceable
   pods are *displaced* — unbound back to pending and fed straight to the
   owning provisioner worker (`ProvisionerWorker.add`), so replacement
   capacity is launching while the drain runs and each pod rebinds exactly
   once. This store has no workload controller to re-create an evicted pod,
   so displacement plays the evict→recreate→reschedule round trip in one
   step; the disruption is PDB-gated like an eviction. Until the escalation
   point the drain is polite: `do-not-evict` pods wait, PDB refusals retry.
   Past `escalate_fraction` of the reclaim window, losing the pod uncleanly
   is strictly worse than any budget, so the drain overrides both — loudly
   (`interruption_drain_override_total{reason}` + warning logs).

3. **Finalizer-path deletion.** Once no replaceable pods remain, the node is
   deleted through the normal finalizer path (termination controller drains
   the daemon-pod tail and calls the cloud delete), so instancegc /
   crash-consistency invariants hold unchanged.

Crash consistency: `interruption.after-annotate` / `interruption.mid-drain`
/ `interruption.before-delete` are named crashpoints; the battletest
(tests/test_interruption.py, `make interruption-smoke`) kills the controller
at each and asserts a restart converges with every pod bound exactly once.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.cloudprovider import (
    HARD_INTERRUPTION_KINDS,
    CloudProvider,
    InterruptionEvent,
    NodeSpec,
)
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.errors import PDBViolationError
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.termination import TerminationController
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.crashpoints import crashpoint
from karpenter_tpu.utils.metrics import REGISTRY

SWEEP_SECONDS = 2.0
# Fraction of the reclaim window spent draining politely before the drain
# overrides do-not-evict and PDB budgets rather than losing pods uncleanly.
DEFAULT_ESCALATE_FRACTION = 0.5

INTERRUPTION_EVENTS_TOTAL = REGISTRY.counter(
    "interruption_events_total",
    "Provider interruption notices received, by kind",
    ["kind"],
)
INTERRUPTION_UNMATCHED_TOTAL = REGISTRY.counter(
    "interruption_events_unmatched_total",
    "Interruption notices that matched no cluster Node (already gone)",
)
INTERRUPTION_OVERRIDE_TOTAL = REGISTRY.counter(
    "interruption_drain_override_total",
    "Deadline-escalated displacements that overrode a protection",
    ["reason"],
)
INTERRUPTION_DISPLACED_TOTAL = REGISTRY.counter(
    "interruption_displaced_pods_total",
    "Pods displaced off interrupted nodes into the provisioner",
)
INTERRUPTION_ACTIVE_NODES = REGISTRY.gauge(
    "interruption_active_nodes",
    "Nodes currently draining under an interruption notice",
)
# Margin left on the reclaim clock when the node entered the finalizer path:
# shrinking lead means drains are racing the deadline — raise capacity or
# lower the escalation fraction.
INTERRUPTION_DRAIN_LEAD = REGISTRY.histogram(
    "interruption_drain_lead_seconds",
    "Seconds of reclaim deadline remaining when the drained node was deleted",
    buckets=(1.0, 5.0, 10.0, 30.0, 60.0, 90.0, 120.0, 300.0),
)


class InterruptionController:
    """Periodic sweep (Manager drives it like instancegc): map provider
    interruption events to nodes, drain ahead of the deadline, replace
    before the pods land."""

    def __init__(
        self,
        cluster: Cluster,
        cloud: CloudProvider,
        provisioning: ProvisioningController,
        termination: TerminationController,
        escalate_fraction: float = DEFAULT_ESCALATE_FRACTION,
        cluster_state=None,
        price_book=None,
    ):
        self.cluster = cluster
        self.cloud = cloud
        self.provisioning = provisioning
        self.termination = termination
        self.escalate_fraction = escalate_fraction
        # The Manager's own PriceBook (market/pricebook.py): interruptions
        # raise the reclaimed pool's forecast hazard. Injected — never read
        # from the process-global active_book(), which with two Managers
        # alive (restart harnesses, parity suites) would attribute THIS
        # manager's interruptions to the OTHER's market state. None = no
        # live market attached (unit harnesses), hazard not tracked.
        self.price_book = price_book
        # Event ids whose hazard was already noted (at-least-once dedup for
        # note_interruption; see _ingest). In-memory: a restart may re-note
        # a redelivered event once, which the half-life decay absorbs.
        self._noted: set = set()
        # Incremental encoder: the drain's replaceable-pod listing reads its
        # O(delta)-maintained per-node index instead of filtering the whole
        # store per node per sweep; displacement itself re-reads the store
        # (reschedule_pod), and the replacement re-solve the displaced pods
        # feed (ProvisionerWorker.add) solves against the same state.
        self.cluster_state = cluster_state
        self.log = klog.named("interruption")
        # node name -> first sweep that saw its interruption; the escalation
        # anchor. In-memory only: after a restart the window re-anchors at
        # the restart (the remaining time to the ANNOTATED deadline shrinks,
        # so escalation can only come sooner, never later than the deadline).
        self._observed: Dict[str, float] = {}

    # --- sweep --------------------------------------------------------------

    def reconcile(self, _key=None) -> float:
        for event in self.cloud.poll_interruptions():
            self._ingest(event)
        draining = []
        for node in self.cluster.list_nodes():
            if wellknown.INTERRUPTION_KIND_ANNOTATION not in node.annotations:
                continue
            if node.deletion_timestamp is not None:
                continue  # the finalizer path owns it now (termination)
            draining.append(node)
        # Prune anchors for nodes that left the drain set — including ones
        # deleted AND fully removed between sweeps (external delete,
        # Liveness/Expiration), which the loop above never visits.
        names = {node.name for node in draining}
        self._observed = {
            name: at for name, at in self._observed.items() if name in names
        }
        for node in draining:
            self._drain(node)
        INTERRUPTION_ACTIVE_NODES.set(float(len(draining)))
        return SWEEP_SECONDS

    # --- ingest (record-then-ack) -------------------------------------------

    def _ingest(self, event: InterruptionEvent) -> None:
        INTERRUPTION_EVENTS_TOTAL.inc(event.kind)
        node = self._match_node(event)
        if node is None:
            # Instance already gone (or never registered — instancegc's
            # problem, not ours): ack so the queue doesn't clog.
            INTERRUPTION_UNMATCHED_TOTAL.inc()
            self.log.info(
                "interruption %s for unmatched instance %s; acked",
                event.kind, event.instance_id,
            )
            self.cloud.ack_interruption(event)
            return
        self._record(node, event)
        # The pool is being reclaimed: black it out so the replacement
        # re-solve excludes it. In-memory, so it sits BEFORE the ack — a
        # crash here re-delivers the event and re-arms the blackout.
        self.cloud.blackout_offering(
            node.instance_type, node.zone, node.capacity_type
        )
        # And raise the pool's interruption hazard: the forecast penalty
        # (market/forecast.py) steers FUTURE packing away from this pool
        # even after the blackout TTL lapses, decaying on a half-life.
        # Deduped per event id: the feed is at-least-once (an ack that
        # fails after retries redelivers), and note_interruption is a
        # counted increment — without the dedup one physical interruption
        # would double its hazard contribution on every redelivery. The
        # blackout above is naturally idempotent; this is not.
        if self.price_book is not None and event.event_id not in self._noted:
            if len(self._noted) >= 4096:
                # Bounded: clear BEFORE adding so the current id survives
                # the flush (old ids never redeliver; the one being
                # processed right now absolutely can — its ack is next).
                self._noted.clear()
            self._noted.add(event.event_id)
            self.price_book.note_interruption((node.instance_type, node.zone))
        crashpoint("interruption.after-annotate")
        self.cloud.ack_interruption(event)

    def _match_node(self, event: InterruptionEvent) -> Optional[NodeSpec]:
        """Join on provider_id when the event carries one, else on the
        instance id suffix of the node's provider id (EC2 events name only
        the instance)."""
        for node in self.cluster.list_nodes():
            if event.provider_id and node.provider_id == event.provider_id:
                return node
            if event.instance_id and node.provider_id.endswith(
                "/" + event.instance_id
            ):
                return node
        return None

    def _record(self, node: NodeSpec, event: InterruptionEvent) -> None:
        """Stamp the interruption onto the Node (idempotent; a harder kind
        or an earlier deadline upgrades a previous stamp)."""
        current = node.annotations.get(wellknown.INTERRUPTION_KIND_ANNOTATION)
        changed = False
        if current is None or (
            event.is_hard() and current not in HARD_INTERRUPTION_KINDS
        ):
            node.annotations[wellknown.INTERRUPTION_KIND_ANNOTATION] = event.kind
            changed = True
        if event.deadline is not None:
            known = self._deadline(node)
            if known is None or event.deadline < known:
                node.annotations[
                    wellknown.INTERRUPTION_DEADLINE_ANNOTATION
                ] = repr(event.deadline)
                changed = True
        if changed:
            self.cluster.update_node(node)
            self.log.warning(
                "node %s (%s %s/%s) interrupted: %s, deadline %s",
                node.name, node.instance_type, node.zone, node.capacity_type,
                event.kind, event.deadline if event.deadline else "none",
            )

    @staticmethod
    def _deadline(node: NodeSpec) -> Optional[float]:
        raw = node.annotations.get(wellknown.INTERRUPTION_DEADLINE_ANNOTATION)
        try:
            return float(raw) if raw else None
        except ValueError:
            return None

    # --- drain ---------------------------------------------------------------

    def _drain(self, node: NodeSpec) -> None:
        self.termination.terminator.cordon(node)
        now = self.cluster.clock.now()
        deadline = self._deadline(node)
        anchor = self._observed.setdefault(node.name, now)
        # Only HARD kinds may escalate — a soft event carrying a deadline
        # (whatever stamped it) still never buys the right to override
        # protections; the capacity is merely at elevated risk.
        hard = (
            node.annotations.get(wellknown.INTERRUPTION_KIND_ANNOTATION)
            in HARD_INTERRUPTION_KINDS
        )
        escalated = (
            hard
            and deadline is not None
            and now >= anchor + (
                self.escalate_fraction * max(0.0, deadline - anchor)
            )
        )
        displaced = [
            self._displace(node, pod, escalated)
            for pod in self._replaceable(node)
        ]
        if not all(displaced):
            return  # protected/PDB-blocked pods wait for the next sweep
        # Drained of everything replaceable: hand the node to the finalizer
        # path (termination drains the daemon tail, deletes at the cloud,
        # strips the finalizer) so instancegc invariants hold unchanged.
        crashpoint("interruption.before-delete")
        self._observed.pop(node.name, None)
        if deadline is not None:
            INTERRUPTION_DRAIN_LEAD.observe(max(0.0, deadline - now))
        self.cluster.delete_node(node.name)
        self.log.info("interrupted node %s drained; deleting", node.name)

    def _replaceable(self, node: NodeSpec) -> List[PodSpec]:
        """Pods worth replacement capacity — the same drain-eligibility
        predicate the terminator's eviction set uses, so the 'nothing
        replaceable left' handoff and the finalizer drain cannot disagree."""
        if self.cluster_state is not None:
            pods = self.cluster_state.pods_on_node(node.name)
        else:
            pods = self.cluster.list_pods(node_name=node.name)
        return [pod for pod in pods if pod.survives_node_drain()]

    def _displace(self, node: NodeSpec, pod: PodSpec, escalated: bool) -> bool:
        """Unbind one pod back to pending and feed it to the provisioner.
        Polite before escalation (do-not-evict waits, PDB refusals retry);
        past it, overrides are taken — and counted — rather than letting the
        reclaim kill the pod uncleanly."""
        protected = wellknown.DO_NOT_EVICT_ANNOTATION in pod.annotations
        if protected and not escalated:
            return False
        try:
            live = self.cluster.reschedule_pod(pod.namespace, pod.name)
        except PDBViolationError:
            if not escalated:
                return False
            live = self.cluster.reschedule_pod(
                pod.namespace, pod.name, override_pdb=True
            )
            INTERRUPTION_OVERRIDE_TOTAL.inc("pdb")
            self.log.warning(
                "deadline escalation: displacing %s/%s from %s OVER its PDB",
                pod.namespace, pod.name, node.name,
            )
        if live is None:
            return True  # vanished under us: nothing left to replace
        if protected:
            INTERRUPTION_OVERRIDE_TOTAL.inc("do-not-evict")
            self.log.warning(
                "deadline escalation: displacing %s/%s from %s despite "
                "do-not-evict", pod.namespace, pod.name, node.name,
            )
        INTERRUPTION_DISPLACED_TOTAL.inc()
        crashpoint("interruption.mid-drain")
        self._feed(node, live)
        return True

    def _feed(self, node: NodeSpec, pod: PodSpec) -> None:
        """Proactive replacement: hand the displaced pod straight to the
        owning provisioner's batch window (skipping a selection round trip)
        so replacement capacity is launching while the rest of the drain
        runs. Without a worker (foreign node, provisioner deleted) the
        reschedule's watch event still routes the pod through selection."""
        name = node.labels.get(wellknown.PROVISIONER_NAME_LABEL, "")
        worker = self.provisioning.worker(name)
        if worker is not None:
            worker.add(pod)
