"""Termination: finalizer-driven graceful node deletion.

Ref: pkg/controllers/termination/{controller,terminate,eviction}.go — a node
with a deletionTimestamp and the karpenter termination finalizer is cordoned,
drained (respecting do-not-evict, PDBs, and critical-pod ordering), then
deleted at the cloud provider before the finalizer is removed.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.cloudprovider import CloudProvider, NodeSpec
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.errors import PDBViolationError
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.workqueue import BackoffQueue

CRITICAL_PRIORITY_CLASSES = ("system-cluster-critical", "system-node-critical")


class EvictionQueue:
    """Async rate-limited eviction worker (ref: termination/eviction.go:45-109):
    set-deduped, exponential backoff 100ms -> 10s, PDB violations retry.

    The queue drains from its OWN pump thread (start()/stop()), independent of
    any termination reconcile — the reference runs a standalone worker
    goroutine (eviction.go:45-57), so queued evictions survive a node whose
    reconcile stops requeueing. Tests without a runtime call drain_once()."""

    PUMP_INTERVAL_SECONDS = 0.1

    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self.queue = BackoffQueue(base_delay=0.1, max_delay=10.0, clock=cluster.clock)
        self.log = klog.named("eviction")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add(self, pods: List[PodSpec]) -> None:
        for pod in pods:
            self.queue.add((pod.namespace, pod.name))

    def drain_once(self) -> int:
        """Pump the queue once (the pump thread loops this; tests call it
        directly)."""

        def evict(key) -> bool:
            namespace, name = key
            pod = self.cluster.try_get_pod(namespace, name)
            if pod is None:
                return True
            try:
                self.cluster.evict_pod(namespace, name)
                return True
            except PDBViolationError:
                return False  # 429-equivalent: retry with backoff

        return self.queue.process(evict)

    def start(self) -> None:
        """Start the standalone pump thread (idempotent). Each pump owns its
        stop Event: a pump that outlived its stop()'s join timeout keeps its
        already-set Event and still exits, instead of being revived by the
        next start()."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump, args=(self._stop,), name="eviction-queue", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _pump(self, stop: threading.Event) -> None:
        while not stop.wait(timeout=self.PUMP_INTERVAL_SECONDS):
            try:
                self.drain_once()
            except Exception:  # noqa: BLE001 — the pump must survive store errors
                self.log.exception("eviction drain failed")


class Terminator:
    """Ref: termination/terminate.go."""

    def __init__(self, cluster: Cluster, cloud: CloudProvider, evictions: EvictionQueue):
        self.cluster = cluster
        self.cloud = cloud
        self.evictions = evictions

    def cordon(self, node: NodeSpec) -> None:
        """ref: terminate.go:42-55."""
        if not node.unschedulable:
            node.unschedulable = True
            self.cluster.update_node(node)

    def drain(self, node: NodeSpec) -> bool:
        """Returns True when fully drained (ref: terminate.go:58-82)."""
        pods = self.cluster.list_pods(node_name=node.name)
        # Refuse to drain while any pod carries do-not-evict
        # (ref: terminate.go:67-72).
        for pod in pods:
            if wellknown.DO_NOT_EVICT_ANNOTATION in pod.annotations:
                return False
        evictable = self._evictable(pods)
        if not evictable:
            return True
        # Evict non-critical pods before critical ones
        # (ref: terminate.go:127-147).
        non_critical = [
            p for p in evictable
            if p.priority_class_name not in CRITICAL_PRIORITY_CLASSES
        ]
        self.evictions.add(non_critical if non_critical else evictable)
        return False

    def _evictable(self, pods: List[PodSpec]) -> List[PodSpec]:
        """Skip terminating ("stuck") and node-owned/daemon pods that tolerate
        the unschedulable state (ref: terminate.go:111-125)."""
        out = []
        for pod in pods:
            if pod.is_terminating() or pod.is_terminal():
                continue
            if pod.is_owned_by_node() or pod.is_owned_by_daemonset():
                continue
            out.append(pod)
        return out

    def terminate(self, node: NodeSpec) -> None:
        """Cloud delete then strip the finalizer (ref: terminate.go:84-100)."""
        self.cloud.delete(node)
        self.cluster.remove_finalizer(node, wellknown.TERMINATION_FINALIZER)


class TerminationController:
    """Ref: termination/controller.go:60-97. Requeues (returning a delay)
    while draining."""

    REQUEUE_SECONDS = 1.0

    def __init__(self, cluster: Cluster, cloud: CloudProvider):
        self.cluster = cluster
        self.evictions = EvictionQueue(cluster)
        self.terminator = Terminator(cluster, cloud, self.evictions)

    def reconcile(self, name: str) -> Optional[float]:
        node = self.cluster.try_get_node(name)
        if node is None:
            return None
        if node.deletion_timestamp is None:
            return None
        if wellknown.TERMINATION_FINALIZER not in node.finalizers:
            return None
        self.terminator.cordon(node)
        if not self.terminator.drain(node):
            # Evictions drain from the EvictionQueue's own pump thread
            # (ref: eviction.go:45-57) — the reconcile only requeues to
            # observe progress.
            return self.REQUEUE_SECONDS
        self.terminator.terminate(node)
        return None
