"""Termination: finalizer-driven graceful node deletion.

Ref: pkg/controllers/termination/{controller,terminate,eviction}.go — a node
with a deletionTimestamp and the karpenter termination finalizer is cordoned,
drained (respecting do-not-evict, PDBs, and critical-pod ordering), then
deleted at the cloud provider before the finalizer is removed.
"""

from __future__ import annotations

import threading
from typing import Dict, FrozenSet, List, Optional, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.cloudprovider import CloudProvider, NodeSpec
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.errors import PDBViolationError
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.fence import bind_thread
from karpenter_tpu.utils.metrics import REGISTRY
from karpenter_tpu.utils.workqueue import BackoffQueue

CRITICAL_PRIORITY_CLASSES = ("system-cluster-critical", "system-node-critical")

# Eviction outcomes by result: "evicted" is progress, "pdb-blocked" retries
# with backoff, "gone" means the pod vanished before the queue reached it.
EVICTIONS_TOTAL = REGISTRY.counter(
    "evictions_total", "Evictions processed by the eviction queue", ["result"]
)
# Cordon-to-cloud-delete wall time per drained node. Buckets stretch past the
# reconcile-duration ramp: a drain legitimately lasts minutes when PDBs
# meter it.
NODE_DRAIN_DURATION = REGISTRY.histogram(
    "node_drain_duration_seconds",
    "Node drain duration (first drain attempt to cloud delete)",
    buckets=(1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1800.0, 3600.0),
)
# A drain that spins without progress was previously invisible (the
# reconcile requeued forever in silence); this fires once per stall episode.
DRAIN_STALLED_TOTAL = REGISTRY.counter(
    "drain_stalled_total",
    "Drains that made no progress for STALL_RECONCILES consecutive "
    "reconciles, by blocking reason",
    ["reason"],
)


class EvictionQueue:
    """Async rate-limited eviction worker (ref: termination/eviction.go:45-109):
    set-deduped, exponential backoff 100ms -> 10s, PDB violations retry.

    The queue drains from its OWN pump thread (start()/stop()), independent of
    any termination reconcile — the reference runs a standalone worker
    goroutine (eviction.go:45-57), so queued evictions survive a node whose
    reconcile stops requeueing. Tests without a runtime call drain_once()."""

    PUMP_INTERVAL_SECONDS = 0.1

    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self.queue = BackoffQueue(base_delay=0.1, max_delay=10.0, clock=cluster.clock)
        self.log = klog.named("eviction")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add(self, pods: List[PodSpec]) -> None:
        for pod in pods:
            self.queue.add((pod.namespace, pod.name))

    def drain_once(self) -> int:
        """Pump the queue once (the pump thread loops this; tests call it
        directly)."""

        def evict(key) -> bool:
            namespace, name = key
            pod = self.cluster.try_get_pod(namespace, name)
            if pod is None:
                EVICTIONS_TOTAL.inc("gone")
                return True
            try:
                self.cluster.evict_pod(namespace, name)
                EVICTIONS_TOTAL.inc("evicted")
                return True
            except PDBViolationError:
                EVICTIONS_TOTAL.inc("pdb-blocked")
                return False  # 429-equivalent: retry with backoff

        return self.queue.process(evict)

    def start(self) -> None:
        """Start the standalone pump thread (idempotent). Each pump owns its
        stop Event: a pump that outlived its stop()'s join timeout keeps its
        already-set Event and still exits, instead of being revived by the
        next start()."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._pump, args=(self._stop,), name="eviction-queue", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _pump(self, stop: threading.Event) -> None:
        # The pump evicts through the store's fenced verbs: bind the fence
        # so a deposed leader's pump aborts at the crashpoint gate between
        # drains, not only at the next evict_pod's fence check.
        bind_thread(self.cluster.fence)
        while not stop.wait(timeout=self.PUMP_INTERVAL_SECONDS):
            try:
                self.drain_once()
            except Exception:  # noqa: BLE001 — the pump must survive store errors
                self.log.exception("eviction drain failed")


class Terminator:
    """Ref: termination/terminate.go."""

    def __init__(self, cluster: Cluster, cloud: CloudProvider, evictions: EvictionQueue):
        self.cluster = cluster
        self.cloud = cloud
        self.evictions = evictions
        # node name -> clock time of the FIRST drain attempt; closed (and
        # observed into NODE_DRAIN_DURATION) at terminate.
        self._drain_started: Dict[str, float] = {}

    def cordon(self, node: NodeSpec) -> None:
        """ref: terminate.go:42-55."""
        if not node.unschedulable:
            node.unschedulable = True
            self.cluster.update_node(node)

    def drain(self, node: NodeSpec) -> bool:
        """Returns True when fully drained (ref: terminate.go:58-82)."""
        first_attempt = node.name not in self._drain_started
        self._drain_started.setdefault(node.name, self.cluster.clock.now())
        pods = self.cluster.list_pods(node_name=node.name)
        if first_attempt:
            # Flight-record the drain DECISION once, at first attempt — the
            # black box names which node started displacing pods and when.
            from karpenter_tpu.utils.obs import RECORDER

            RECORDER.record("drain", node=node.name, pods=len(pods))
        # Refuse to drain while any pod carries do-not-evict
        # (ref: terminate.go:67-72).
        for pod in pods:
            if wellknown.DO_NOT_EVICT_ANNOTATION in pod.annotations:
                return False
        evictable = self._evictable(pods)
        if not evictable:
            return True
        # Evict non-critical pods before critical ones
        # (ref: terminate.go:127-147).
        non_critical = [
            p for p in evictable
            if p.priority_class_name not in CRITICAL_PRIORITY_CLASSES
        ]
        self.evictions.add(non_critical if non_critical else evictable)
        return False

    def _evictable(self, pods: List[PodSpec]) -> List[PodSpec]:
        """Skip terminating ("stuck") and node-owned/daemon pods that tolerate
        the unschedulable state (ref: terminate.go:111-125)."""
        return [pod for pod in pods if pod.survives_node_drain()]

    def terminate(self, node: NodeSpec) -> None:
        """Cloud delete then strip the finalizer (ref: terminate.go:84-100)."""
        # The provider call is outside the store, so the deposed-leader
        # fence check runs here at the caller (utils/fence.py).
        self.cluster.fence.check("cloud.delete")
        self.cloud.delete(node)
        self.cluster.remove_finalizer(node, wellknown.TERMINATION_FINALIZER)
        started = self._drain_started.pop(node.name, None)
        if started is not None:
            NODE_DRAIN_DURATION.observe(self.cluster.clock.now() - started)

    def forget(self, name: str) -> None:
        """Drop drain bookkeeping for a node that vanished without passing
        through terminate (external delete raced us)."""
        self._drain_started.pop(name, None)


class TerminationController:
    """Ref: termination/controller.go:60-97. Requeues (returning a delay)
    while draining."""

    REQUEUE_SECONDS = 1.0
    # Reconciles without drain progress before the stall is surfaced (at the
    # 1s requeue that is ~30s of a node visibly going nowhere).
    STALL_RECONCILES = 30

    def __init__(self, cluster: Cluster, cloud: CloudProvider):
        self.cluster = cluster
        self.evictions = EvictionQueue(cluster)
        self.terminator = Terminator(cluster, cloud, self.evictions)
        self.log = klog.named("termination")
        # node name -> (pod-state snapshot, consecutive no-change count).
        # Progress = the snapshot changes (a pod vanished or started
        # terminating); a long-flat snapshot is a stalled drain.
        self._stalls: Dict[str, Tuple[FrozenSet, int]] = {}

    def reconcile(self, name: str) -> Optional[float]:
        node = self.cluster.try_get_node(name)
        if node is None:
            self._stalls.pop(name, None)
            self.terminator.forget(name)
            return None
        if node.deletion_timestamp is None:
            return None
        if wellknown.TERMINATION_FINALIZER not in node.finalizers:
            return None
        self.terminator.cordon(node)
        if not self.terminator.drain(node):
            # Evictions drain from the EvictionQueue's own pump thread
            # (ref: eviction.go:45-57) — the reconcile only requeues to
            # observe progress.
            self._observe_stall(node)
            return self.REQUEUE_SECONDS
        self.terminator.terminate(node)
        self._stalls.pop(name, None)
        return None

    def _observe_stall(self, node: NodeSpec) -> None:
        """Count consecutive no-progress reconciles; at STALL_RECONCILES,
        increment drain_stalled_total{reason} and log the blocking pods ONCE
        per stall episode (progress resets the episode)."""
        pods = self.cluster.list_pods(node_name=node.name)
        snapshot = frozenset(
            (p.namespace, p.name, p.is_terminating()) for p in pods
        )
        previous, count = self._stalls.get(node.name, (None, 0))
        if snapshot != previous:
            self._stalls[node.name] = (snapshot, 0)
            return
        count += 1
        self._stalls[node.name] = (snapshot, count)
        if count != self.STALL_RECONCILES:
            return
        blockers = [
            p for p in pods if wellknown.DO_NOT_EVICT_ANNOTATION in p.annotations
        ]
        reason = "do-not-evict" if blockers else "pdb"
        DRAIN_STALLED_TOTAL.inc(reason)
        stuck = blockers or [p for p in pods if not p.is_terminating()]
        self.log.warning(
            "drain of %s stalled for %d reconciles (%s); blocking pods: %s",
            node.name,
            count,
            reason,
            ", ".join(sorted(f"{p.namespace}/{p.name}" for p in stuck)) or "none",
        )
