"""Selection: route unschedulable pods to provisioners.

Ref: pkg/controllers/selection/{controller,preferences}.go — watches all pods
(MaxConcurrentReconciles 10,000 in the reference; our runtime fans out over a
thread pool), filters provisionable ones, rejects unsupported scheduling
features, and hands the pod to the first matching provisioner in alphabetical
order.

Preference relaxation no longer lives here: the reference re-ran the whole
schedule once per relaxation level across retries (preferences.go:64-106);
the constraint compiler now lowers the full ladder into the [L, G, T] kernel
dispatch (constraints/), which solves every level at once and picks the
strictest feasible one on device. The UID-keyed TTL cache survives as the
BOOKKEEPING layer: the provisioning worker records the kernel-chosen level
per pod after each constrained solve (Preferences.record), preserving the
reference's observability (which pods are running relaxed, at what level)
without the retry loop or its detached-copy re-solve.
"""

from __future__ import annotations

from typing import Optional, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import DO_NOT_SCHEDULE, PodSpec
from karpenter_tpu.api.provisioner import PodIncompatibleError
from karpenter_tpu.api.requirements import SUPPORTED_OPERATORS
from karpenter_tpu.constraints import greedy_topology_enabled
from karpenter_tpu.constraints.terms import term_topology_key
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.scheduling import SUPPORTED_TOPOLOGY_KEYS
from karpenter_tpu.utils.cache import TtlCache
from karpenter_tpu.utils.clock import Clock
from karpenter_tpu.utils.obs import OBS


class UnsupportedPodError(Exception):
    """The pod uses features the provisioning path doesn't support
    (ref: selection/controller.go validate:108-159)."""


class Preferences:
    """UID-keyed TTL cache of each pod's kernel-chosen relaxation level
    (ref: selection/preferences.go:40-106 — the reference stored the relaxed
    terms themselves and re-drove the solve; the kernel now solves every
    level in one dispatch, so this cache records the OUTCOME). The stored
    pod spec is never mutated. Entries expire on their own TTL, matching the
    reference's go-cache: a pod that stops being re-solved for five minutes
    simply ages out."""

    TTL_SECONDS = 300.0

    def __init__(self, clock: Optional[Clock] = None):
        self._cache = TtlCache(self.TTL_SECONDS, clock)

    def record(self, uid: str, level: int, description: str = "") -> None:
        """Record the level the [L, G, T] dispatch chose for this pod's
        schedule (called by the provisioning worker after each constrained
        solve). Level 0 = full preferences honored — recorded too, so
        `level()` distinguishes "solved strict" from "never solved"."""
        self._cache.set(uid, (int(level), description))

    def level(self, pod_or_uid) -> Optional[int]:
        uid = getattr(pod_or_uid, "uid", pod_or_uid)
        entry: Optional[Tuple[int, str]] = self._cache.get(uid)
        return None if entry is None else entry[0]

    def describe(self, pod_or_uid) -> Optional[str]:
        uid = getattr(pod_or_uid, "uid", pod_or_uid)
        entry: Optional[Tuple[int, str]] = self._cache.get(uid)
        return None if entry is None else entry[1]

    def forget(self, uid: str) -> None:
        self._cache.delete(uid)


class SelectionController:
    """Ref: selection/controller.go:55-102."""

    # Re-verify cadence for pods a worker has ACCEPTED (batched or in its
    # overflow backlog): the worker owns delivery from here and watch events
    # still pull the key forward immediately, so the safety re-verify can be
    # slow — at 1 Hz a 50k-pod backlog burns the GIL on no-op reconciles
    # (measured: ~15s of queue mechanics per 2000-pod batch).
    ACCEPTED_REQUEUE_SECONDS = 5.0
    # Exponential backoff for pods no provisioner matches, mirroring
    # workqueue.DefaultControllerRateLimiter (5ms→1000s) that the reference
    # gets for free when it returns the match error. Our reconcile loop tick
    # floors the base at 1s; the cap matches the reference's 1000s.
    BACKOFF_BASE_SECONDS = 1.0
    BACKOFF_MAX_SECONDS = 1000.0
    # Backoff cap for pods REFUSED at a full provisioning queue
    # (--provision-queue-max-pods): unlike a no-match, the queue drains at
    # batch cadence, so the retry ceiling stays tight — the pod keeps aging
    # on its lifecycle anchor and re-enters the worker's aging-ordered
    # refill as soon as admission reopens.
    REFUSED_BACKOFF_MAX_SECONDS = 30.0

    def __init__(self, cluster: Cluster, provisioning: ProvisioningController):
        self.cluster = cluster
        self.provisioning = provisioning
        self.preferences = Preferences(cluster.clock)
        # The provisioning workers report each constrained solve's chosen
        # relaxation level back through this hook — selection owns the
        # bookkeeping cache, provisioning owns the solve.
        provisioning.level_recorder = self.preferences.record
        # UID → consecutive no-match failures; entries expire on their own so
        # deleted pods don't leak state.
        self._failures = TtlCache(2 * self.BACKOFF_MAX_SECONDS, cluster.clock)

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        pod = self.cluster.try_get_pod(namespace, name)
        if pod is None or not pod.is_provisionable():
            return None
        # Lifecycle anchor for harness-driven paths (the Manager path also
        # anchors from the watch-delta feed; first sight wins in both).
        OBS.first_seen(pod)
        try:
            self._validate(pod)
        except UnsupportedPodError:
            return None  # ignored; kube-scheduler owns it (ref: :70-75)

        # Hand the STORED pod over untouched: the scheduler compiles its
        # full relaxation ladder into the solve, so there is no relaxed copy
        # to fabricate here (the old detached-copy re-solve loop is gone).
        outcome = self._select_and_enqueue(pod)
        if outcome == "accepted":
            self._failures.delete(pod.uid)
            return self.ACCEPTED_REQUEUE_SECONDS
        # No provisioner matched, or the matching worker's admission queue
        # is full. The retry happens anyway — the reference returns the
        # match error so controller-runtime keeps requeueing
        # (selectProvisioner:80-102), which is what heals a pod whose
        # provisioner appears (or widens) later — but with exponential
        # backoff, so a permanently-unschedulable pod isn't polled at 1 Hz
        # forever. (Relaxation cannot help a no-match: every ladder level is
        # already solved inside the kernel dispatch once a worker accepts.)
        failures = self._failures.get(pod.uid) or 0
        self._failures.set(pod.uid, failures + 1)
        # min() on the exponent too: the counter keeps growing for a pod
        # that never schedules, and 2.0**1024 overflows.
        cap = (
            self.REFUSED_BACKOFF_MAX_SECONDS
            if outcome == "refused"
            else self.BACKOFF_MAX_SECONDS
        )
        return min(self.BACKOFF_BASE_SECONDS * (2.0 ** min(failures, 16)), cap)

    def _validate(self, pod: PodSpec) -> None:
        greedy = greedy_topology_enabled()
        SelectionController._validate_affinity(pod, greedy)
        if pod.match_fields_terms:
            raise UnsupportedPodError("node affinity matchFields is not supported")
        if greedy:
            for constraint in pod.topology_spread:
                if constraint.topology_key not in SUPPORTED_TOPOLOGY_KEYS:
                    raise UnsupportedPodError(
                        f"topology key {constraint.topology_key!r} is not supported"
                    )
        for terms in [
            *[term.requirements for term in pod.preferred_terms],
            *pod.required_terms,
        ]:
            for requirement in terms:
                if requirement.operator not in SUPPORTED_OPERATORS:
                    raise UnsupportedPodError(
                        f"operator {requirement.operator!r} is not supported"
                    )

    @staticmethod
    def _validate_affinity(pod: PodSpec, greedy: bool) -> None:
        for term in pod.pod_affinity_terms:
            key = term_topology_key(term)
            if greedy or key == wellknown.HOSTNAME_LABEL:
                # Hostname affinity ("pack my pods onto one node") has no
                # sound lowering onto fresh nodes; the greedy oracle path
                # keeps the reference's blanket rejection.
                raise UnsupportedPodError("pod affinity on this key is not supported")
            if key != wellknown.ZONE_LABEL and not any(
                c.topology_key == key for c in pod.topology_spread
            ):
                # Affinity on a custom key needs that key's spread
                # constraint to give fresh nodes a domain (labels are
                # stamped at registration); without it the compiler has no
                # sound lowering and would silently drop the term.
                raise UnsupportedPodError(
                    f"pod affinity on key {key!r} requires a topology spread "
                    "constraint on the same key"
                )
        if greedy and pod.pod_anti_affinity_terms:
            raise UnsupportedPodError("pod anti-affinity is not supported")
        for term in pod.pod_anti_affinity_terms:
            key = term_topology_key(term)
            if key in (wellknown.HOSTNAME_LABEL, wellknown.ZONE_LABEL):
                continue
            if not any(
                c.topology_key == key
                and c.when_unsatisfiable == DO_NOT_SCHEDULE
                for c in pod.topology_spread
            ):
                # The compiler only lowers custom-key exclusions for the
                # domain-expanded (hard) spread key; accepting anything else
                # would silently drop the constraint (the reference rejects
                # these pods so kube-scheduler owns them).
                raise UnsupportedPodError(
                    f"pod anti-affinity on key {key!r} requires a "
                    "DoNotSchedule topology spread constraint on the same key"
                )

    def _select_and_enqueue(self, pod: PodSpec) -> str:
        """Highest-weight matching provisioner wins; alphabetical order
        breaks ties (ref: selectProvisioner:80-102, plus real Karpenter's
        `.spec.weight` preference). Outcomes: "accepted" (a worker holds the
        pod — batch window or overflow), "refused" (the matching worker's
        admission queue is at --provision-queue-max-pods; the pod stays on
        the requeue ladder and ages there), "no-match"."""
        ranked = sorted(
            self.cluster.list_provisioners(),
            key=lambda p: (-p.spec.weight, p.name),
        )
        for provisioner in ranked:
            if provisioner.deletion_timestamp is not None:
                continue
            worker = self.provisioning.worker(provisioner.name)
            if worker is None:
                continue
            try:
                # Validate against the worker's EFFECTIVE constraints (fleet
                # -refreshed requirements), matching the reference where
                # selection reads the provisioning controller's in-memory
                # provisioners (ref: selectProvisioner:80-102) — the stored
                # spec is pristine and intentionally wider.
                self._compatible(worker, pod)
            except PodIncompatibleError:
                continue
            # First match decides: a refusal here must NOT fall through to a
            # later (alphabetically lower-priority) provisioner — that would
            # flip placement priority under load and flap back after drain.
            return "accepted" if worker.add(pod) else "refused"
        return "no-match"

    @staticmethod
    def _compatible(worker, pod: PodSpec) -> None:
        """Raise PodIncompatibleError unless SOME relaxation level of the
        pod fits the worker's constraints — level 0 alone would wrongly
        bounce a pod whose impossible preference the kernel ladder will
        drop (the legacy path healed this across relax-retry rounds)."""
        constraints = worker.provisioner.spec.constraints
        try:
            constraints.validate_pod(pod)
            return
        except PodIncompatibleError:
            if not pod.preferred_terms and len(pod.required_terms) <= 1:
                raise
        from karpenter_tpu.constraints.ladder import build_ladder
        from karpenter_tpu.controllers.scheduling import Scheduler

        for state in build_ladder(pod).states[1:]:
            try:
                constraints.validate_pod(Scheduler._level_shadow(pod, state))
                return
            except PodIncompatibleError:
                continue
        raise PodIncompatibleError(
            f"pod {pod.namespace}/{pod.name} incompatible at every relaxation level"
        )
