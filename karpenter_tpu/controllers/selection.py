"""Selection: route unschedulable pods to provisioners.

Ref: pkg/controllers/selection/{controller,preferences}.go — watches all pods
(MaxConcurrentReconciles 10,000 in the reference; our runtime fans out over a
thread pool), filters provisionable ones, rejects unsupported scheduling
features, relaxes preferences on retry, and hands the pod to the first
matching provisioner in alphabetical order.
"""

from __future__ import annotations

from typing import List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import PodIncompatibleError
from karpenter_tpu.api.requirements import SUPPORTED_OPERATORS
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.scheduling import SUPPORTED_TOPOLOGY_KEYS


class UnsupportedPodError(Exception):
    """The pod uses features the provisioning path doesn't support
    (ref: selection/controller.go validate:108-159)."""


class Preferences:
    """Iterative relaxation for pods that keep failing to schedule
    (ref: selection/preferences.go:50-106): first drop the heaviest preferred
    term, then drop leading required OR-terms so later alternatives get
    tried. Pods are live objects in our store, so relaxation mutates the pod
    instead of maintaining the reference's UID-keyed TTL cache."""

    def relax(self, pod: PodSpec) -> bool:
        if pod.preferred_terms:
            heaviest = max(pod.preferred_terms, key=lambda term: term.weight)
            pod.preferred_terms.remove(heaviest)
            return True
        if len(pod.required_terms) > 1:
            pod.required_terms.pop(0)
            return True
        return False


class SelectionController:
    """Ref: selection/controller.go:55-102."""

    REQUEUE_SECONDS = 1.0  # re-verify after handing off (ref: :77)

    def __init__(self, cluster: Cluster, provisioning: ProvisioningController):
        self.cluster = cluster
        self.provisioning = provisioning
        self.preferences = Preferences()

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        pod = self.cluster.try_get_pod(namespace, name)
        if pod is None or not pod.is_provisionable():
            return None
        try:
            self._validate(pod)
        except UnsupportedPodError:
            return None  # ignored; kube-scheduler owns it (ref: :70-75)

        matched, enqueued = self._select_and_enqueue(pod)
        if enqueued:
            return self.REQUEUE_SECONDS
        if matched:
            # A provisioner tolerates the pod but its batch is full — retry
            # without corrupting the pod's preferences (relaxation is only
            # for genuine incompatibility; ref: preferences.go:50-63).
            return self.REQUEUE_SECONDS
        # No provisioner matched: relax and retry if anything was relaxable.
        if self.preferences.relax(pod):
            return self.REQUEUE_SECONDS
        return None

    def _validate(self, pod: PodSpec) -> None:
        if pod.pod_affinity_terms:
            raise UnsupportedPodError("pod affinity is not supported")
        if pod.pod_anti_affinity_terms:
            raise UnsupportedPodError("pod anti-affinity is not supported")
        if pod.match_fields_terms:
            raise UnsupportedPodError("node affinity matchFields is not supported")
        for constraint in pod.topology_spread:
            if constraint.topology_key not in SUPPORTED_TOPOLOGY_KEYS:
                raise UnsupportedPodError(
                    f"topology key {constraint.topology_key!r} is not supported"
                )
        for terms in [
            *[term.requirements for term in pod.preferred_terms],
            *pod.required_terms,
        ]:
            for requirement in terms:
                if requirement.operator not in SUPPORTED_OPERATORS:
                    raise UnsupportedPodError(
                        f"operator {requirement.operator!r} is not supported"
                    )

    def _select_and_enqueue(self, pod: PodSpec):
        """First matching provisioner in alphabetical order wins
        (ref: selectProvisioner:80-102). Returns (matched, enqueued)."""
        for provisioner in self.cluster.list_provisioners():
            if provisioner.deletion_timestamp is not None:
                continue
            worker = self.provisioning.worker(provisioner.name)
            if worker is None:
                continue
            try:
                # Validate against the worker's EFFECTIVE constraints (fleet
                # -refreshed requirements), matching the reference where
                # selection reads the provisioning controller's in-memory
                # provisioners (ref: selectProvisioner:80-102) — the stored
                # spec is pristine and intentionally wider.
                worker.provisioner.spec.constraints.validate_pod(pod)
            except PodIncompatibleError:
                continue
            return True, worker.add(pod)
        return False, False
