"""Selection: route unschedulable pods to provisioners.

Ref: pkg/controllers/selection/{controller,preferences}.go — watches all pods
(MaxConcurrentReconciles 10,000 in the reference; our runtime fans out over a
thread pool), filters provisionable ones, rejects unsupported scheduling
features, relaxes preferences on retry, and hands the pod to the first
matching provisioner in alphabetical order.
"""

from __future__ import annotations

import copy
from typing import List, Optional, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec, PreferredTerm
from karpenter_tpu.api.provisioner import PodIncompatibleError
from karpenter_tpu.api.requirements import Requirement, SUPPORTED_OPERATORS
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.scheduling import SUPPORTED_TOPOLOGY_KEYS
from karpenter_tpu.utils.cache import TtlCache
from karpenter_tpu.utils.clock import Clock


class UnsupportedPodError(Exception):
    """The pod uses features the provisioning path doesn't support
    (ref: selection/controller.go validate:108-159)."""


# One pod's relaxation state: (preferred terms left, required OR-terms left).
_RelaxState = Tuple[List[PreferredTerm], List[List[Requirement]]]


class Preferences:
    """UID-keyed relaxation side-cache for pods that keep failing to schedule
    (ref: selection/preferences.go:40-106): first drop the heaviest preferred
    term, then drop leading required OR-terms so later alternatives get tried.

    The stored pod spec is never mutated — relaxation lives in this cache and
    the selection path schedules a detached copy carrying the relaxed terms.
    Like the reference's go-cache, the TTL refreshes only when a relax step
    actually happens (Set, not Get): a pod stuck for five minutes gets its
    full preferences back and the relaxation cycle restarts."""

    TTL_SECONDS = 300.0

    def __init__(self, clock: Optional[Clock] = None):
        self._cache = TtlCache(self.TTL_SECONDS, clock)

    def current(self, pod: PodSpec) -> PodSpec:
        """The pod as the provisioning path should see it right now: either
        the pod itself (never relaxed) or a detached copy carrying the cached
        relaxation."""
        state = self._cache.get(pod.uid)
        if state is None:
            return pod
        return self._with_terms(pod, state)

    def advance(self, pod: PodSpec) -> bool:
        """Relax one more step after a failed scheduling attempt
        (ref: preferences.go:64-106 relax). Returns False when only the last
        required term remains — that one is never dropped."""
        preferred, required = self._cache.get(pod.uid) or self._copy_terms(pod)
        if preferred:
            heaviest = max(preferred, key=lambda term: term.weight)
            preferred = [term for term in preferred if term is not heaviest]
        elif len(required) > 1:
            required = required[1:]
        else:
            return False
        self._cache.set(pod.uid, (preferred, required))
        return True

    @staticmethod
    def _copy_terms(pod: PodSpec) -> _RelaxState:
        return list(pod.preferred_terms), [list(term) for term in pod.required_terms]

    @staticmethod
    def _with_terms(pod: PodSpec, state: _RelaxState) -> PodSpec:
        shadow = copy.copy(pod)
        shadow.preferred_terms = list(state[0])
        shadow.required_terms = [list(term) for term in state[1]]
        return shadow


class SelectionController:
    """Ref: selection/controller.go:55-102."""

    REQUEUE_SECONDS = 1.0  # fresh attempt (relaxation advanced; ref: :77)
    # Re-verify cadence for pods a worker has ACCEPTED (batched or in its
    # overflow backlog): the worker owns delivery from here and watch events
    # still pull the key forward immediately, so the safety re-verify can be
    # slow — at 1 Hz a 50k-pod backlog burns the GIL on no-op reconciles
    # (measured: ~15s of queue mechanics per 2000-pod batch).
    ACCEPTED_REQUEUE_SECONDS = 5.0
    # Exponential backoff for pods no provisioner matches, mirroring
    # workqueue.DefaultControllerRateLimiter (5ms→1000s) that the reference
    # gets for free when it returns the match error. Our reconcile loop tick
    # floors the base at 1s; the cap matches the reference's 1000s.
    BACKOFF_BASE_SECONDS = 1.0
    BACKOFF_MAX_SECONDS = 1000.0

    def __init__(self, cluster: Cluster, provisioning: ProvisioningController):
        self.cluster = cluster
        self.provisioning = provisioning
        self.preferences = Preferences(cluster.clock)
        # UID → consecutive no-match failures; entries expire on their own so
        # deleted pods don't leak state.
        self._failures = TtlCache(2 * self.BACKOFF_MAX_SECONDS, cluster.clock)

    def reconcile(self, namespace: str, name: str) -> Optional[float]:
        pod = self.cluster.try_get_pod(namespace, name)
        if pod is None or not pod.is_provisionable():
            return None
        try:
            self._validate(pod)
        except UnsupportedPodError:
            return None  # ignored; kube-scheduler owns it (ref: :70-75)

        # Schedule the pod at its current relaxation level. The stored spec
        # is never touched: workers receive a detached relaxed copy
        # (ref: preferences.go keeps relaxation in a UID-keyed TTL cache and
        # provisioner.go:172 deliberately batches the in-memory relaxed pod).
        relaxed = self.preferences.current(pod)
        matched = self._select_and_enqueue(relaxed)
        if matched:
            # Accepted by a worker (batch or overflow backlog): re-verify on
            # the slow cadence; no further relaxation (relaxation is only
            # for genuine incompatibility; ref: preferences.go:50-63).
            self._failures.delete(pod.uid)
            return self.ACCEPTED_REQUEUE_SECONDS
        # No provisioner matched: relax one step if possible, then retry.
        # The retry happens EVEN when relaxation is exhausted — the reference
        # returns the match error so controller-runtime keeps requeueing
        # (selectProvisioner:80-102), which is what heals a pod whose
        # provisioner appears (or widens) later — but with exponential
        # backoff, so a permanently-unschedulable pod isn't polled at 1 Hz
        # forever.
        if self.preferences.advance(pod):
            # A fresh relaxation level is a new scheduling attempt worth
            # retrying promptly.
            self._failures.delete(pod.uid)
            return self.REQUEUE_SECONDS
        failures = self._failures.get(pod.uid) or 0
        self._failures.set(pod.uid, failures + 1)
        # min() on the exponent too: the counter keeps growing for a pod
        # that never schedules, and 2.0**1024 overflows.
        return min(
            self.BACKOFF_BASE_SECONDS * (2.0 ** min(failures, 16)),
            self.BACKOFF_MAX_SECONDS,
        )

    def _validate(self, pod: PodSpec) -> None:
        if pod.pod_affinity_terms:
            raise UnsupportedPodError("pod affinity is not supported")
        if pod.pod_anti_affinity_terms:
            raise UnsupportedPodError("pod anti-affinity is not supported")
        if pod.match_fields_terms:
            raise UnsupportedPodError("node affinity matchFields is not supported")
        for constraint in pod.topology_spread:
            if constraint.topology_key not in SUPPORTED_TOPOLOGY_KEYS:
                raise UnsupportedPodError(
                    f"topology key {constraint.topology_key!r} is not supported"
                )
        for terms in [
            *[term.requirements for term in pod.preferred_terms],
            *pod.required_terms,
        ]:
            for requirement in terms:
                if requirement.operator not in SUPPORTED_OPERATORS:
                    raise UnsupportedPodError(
                        f"operator {requirement.operator!r} is not supported"
                    )

    def _select_and_enqueue(self, pod: PodSpec) -> bool:
        """First matching provisioner in alphabetical order wins
        (ref: selectProvisioner:80-102). True iff a worker accepted the pod
        (workers accept unconditionally — batch window or overflow)."""
        for provisioner in self.cluster.list_provisioners():
            if provisioner.deletion_timestamp is not None:
                continue
            worker = self.provisioning.worker(provisioner.name)
            if worker is None:
                continue
            try:
                # Validate against the worker's EFFECTIVE constraints (fleet
                # -refreshed requirements), matching the reference where
                # selection reads the provisioning controller's in-memory
                # provisioners (ref: selectProvisioner:80-102) — the stored
                # spec is pristine and intentionally wider.
                worker.provisioner.spec.constraints.validate_pod(pod)
            except PodIncompatibleError:
                continue
            worker.add(pod)
            return True
        return False
