"""Scheduler: split a pod batch into groups of isomorphic constraints, with
topology-spread decisions injected as node selectors first.

Ref: pkg/controllers/provisioning/scheduling/{scheduler,topology,
topologygroup}.go. The output Schedules feed the solver one at a time — all
pods in a Schedule are satisfiable by the same tightened constraint set, which
is what lets the solver treat them as one dense tensor problem.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import DO_NOT_SCHEDULE, PodSpec, TopologySpreadConstraint
from karpenter_tpu.api.provisioner import Constraints, PodIncompatibleError, Provisioner
from karpenter_tpu.controllers.cluster import Cluster

SUPPORTED_TOPOLOGY_KEYS = (wellknown.HOSTNAME_LABEL, wellknown.ZONE_LABEL)

_domain_counter = itertools.count(1)


@dataclass
class Schedule:
    """Pods satisfiable by one tightened constraint set
    (ref: scheduler.go:54-58)."""

    constraints: Constraints
    pods: List[PodSpec] = field(default_factory=list)


class TopologyGroup:
    """Greedy spread counter (ref: topologygroup.go:24-68)."""

    def __init__(self, constraint: TopologySpreadConstraint):
        self.constraint = constraint
        self.counts: Dict[str, int] = {}

    def register(self, *domains: str) -> None:
        for domain in domains:
            self.counts.setdefault(domain, 0)

    def increment(self, domain: str) -> None:
        if domain in self.counts:
            self.counts[domain] += 1

    def next_domain(self, allowed: Optional[Sequence[str]] = None) -> Optional[str]:
        """argmin-count domain (mutating: increments the winner)."""
        candidates = [
            d for d in self.counts if allowed is None or d in allowed
        ]
        if not candidates:
            return None
        winner = min(candidates, key=lambda d: (self.counts[d], d))
        self.counts[winner] += 1
        return winner

    def assign_many(self, n: int) -> List[str]:
        """n sequential next_domain() picks, computed in closed form.

        The greedy loop is O(n x domains) Python — a real cost when a 50k-pod
        deployment carries one spread constraint. Observation: assigning a
        pod to domain d for the (j+1)-th time happens at "level" counts[d]+j,
        and greedy always takes the globally smallest (level, name); so the
        whole sequence is the first n slots of {(counts[d]+j, d)} in
        (level, name) order — water-filling + one lexsort, bit-identical to
        the sequential walk (the tensor-style reformulation of
        topologygroup.go:54-68's mutating argmin)."""
        if n <= 0 or not self.counts:
            return []
        names = sorted(self.counts)
        counts = np.array([self.counts[d] for d in names], dtype=np.int64)
        # Smallest water level L with sum(max(0, L - c_d)) >= n.
        lo, hi = int(counts.min()) + 1, int(counts.max()) + n
        while lo < hi:
            mid = (lo + hi) // 2
            if int(np.maximum(0, mid - counts).sum()) >= n:
                hi = mid
            else:
                lo = mid + 1
        level = lo
        full = np.maximum(0, (level - 1) - counts)  # slots strictly below L-1
        remaining = n - int(full.sum())
        takes = full.copy()
        # The last `remaining` picks happen at level L-1, in name order among
        # domains that have a slot there.
        for i in range(len(names)):
            if remaining == 0:
                break
            if counts[i] + full[i] == level - 1:  # next untaken slot is L-1
                takes[i] += 1
                remaining -= 1
        # Per-pod sequence: lexsort the taken slots by (level, name rank).
        domain_idx = np.repeat(np.arange(len(names)), takes)
        levels = np.concatenate(
            [np.arange(counts[i], counts[i] + takes[i]) for i in range(len(names))]
        )
        order = np.lexsort((domain_idx, levels))
        sequence = [names[i] for i in domain_idx[order]]
        for i, name in enumerate(names):
            self.counts[name] += int(takes[i])
        return sequence


class Topology:
    """Injects topology-spread decisions as node selectors
    (ref: topology.go:40-140). Only hostname and zone keys are supported —
    selection rejects the rest before pods get here."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def inject(self, constraints: Constraints, pods: Sequence[PodSpec]) -> None:
        for group_key, group_pods in self._topology_groups(pods).items():
            constraint = group_pods[0][0]
            group = TopologyGroup(constraint)
            members = [pod for _, pod in group_pods]
            if constraint.topology_key == wellknown.HOSTNAME_LABEL:
                self._compute_hostname(group, members)
            else:
                self._compute_zonal(group, constraints, members)
            allowed_per_pod = [
                self._allowed_domains_for_pod(pod, group) for pod in members
            ]
            if group.counts and all(a is None for a in allowed_per_pod):
                # Homogeneous fast path: no pod restricts its domains, so the
                # whole group's greedy sequence computes in closed form.
                for pod, domain in zip(members, group.assign_many(len(members))):
                    pod.node_selector[constraint.topology_key] = domain
                continue
            for pod, allowed in zip(members, allowed_per_pod):
                domain = group.next_domain(allowed)
                if domain is not None:
                    pod.node_selector[constraint.topology_key] = domain

    def _topology_groups(self, pods: Sequence[PodSpec]):
        """Group (constraint, pod) pairs by equivalent spread constraint
        (ref: topology.go:57-75)."""
        groups: Dict[Tuple, List[Tuple[TopologySpreadConstraint, PodSpec]]] = {}
        for pod in pods:
            for constraint in pod.topology_spread:
                if constraint.topology_key not in SUPPORTED_TOPOLOGY_KEYS:
                    continue
                groups.setdefault(constraint.group_key(), []).append(
                    (constraint, pod)
                )
        return groups

    def _compute_hostname(self, group: TopologyGroup, pods: List[PodSpec]) -> None:
        """Fabricate ceil(pods/maxSkew) fresh hostname domains
        (ref: topology.go:95-105 — hostname domains don't exist until nodes
        launch, so the scheduler invents distinct buckets)."""
        num_domains = -(-len(pods) // max(group.constraint.max_skew, 1))
        for _ in range(num_domains):
            group.register(f"host-domain-{next(_domain_counter)}")

    def _compute_zonal(
        self, group: TopologyGroup, constraints: Constraints, pods: List[PodSpec]
    ) -> None:
        """Register allowed zones and count existing matching pods per zone
        from live cluster state (ref: topology.go:112-140)."""
        allowed = constraints.effective_requirements().allowed(wellknown.ZONE_LABEL)
        zones = set()
        for node in self.cluster.list_nodes():
            if node.zone and allowed.contains(node.zone):
                zones.add(node.zone)
        # Zones can also come from the constraint envelope even before any
        # node exists there.
        finite = allowed.finite_values()
        if finite:
            zones |= set(finite)
        group.register(*sorted(zones))
        for pod in self.cluster.list_pods(
            predicate=lambda p: p.node_name is not None
            and group.constraint.matches(p.labels)
        ):
            node = self.cluster.try_get_node(pod.node_name)
            if node is not None and node.zone:
                group.increment(node.zone)

    def _allowed_domains_for_pod(self, pod: PodSpec, group: TopologyGroup):
        """A pod with its own zone/hostname selector restricts its domains."""
        key = group.constraint.topology_key
        selected = pod.node_selector.get(key)
        if selected is not None:
            return [selected]
        allowed = pod.scheduling_requirements().allowed(key)
        if allowed.is_any():
            return None
        return [d for d in group.counts if allowed.contains(d)]


class Scheduler:
    """Ref: scheduling/scheduler.go:67-126."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self.topology = Topology(cluster)

    def solve(
        self, provisioner: Provisioner, pods: Sequence[PodSpec]
    ) -> List[Schedule]:
        constraints = provisioner.spec.constraints
        # Topology decisions are injected into per-pass SHADOW copies, never
        # the live pod: a fabricated zone/hostname selector must not survive a
        # failed launch, or retries stay pinned to a blacked-out domain (the
        # reference works on scheduler-local pod copies too).
        work = [(pod, self._scheduling_copy(pod)) for pod in pods]
        self.topology.inject(constraints, [shadow for _, shadow in work])
        schedules: Dict[Tuple, Schedule] = {}
        ordered: List[Schedule] = []
        # validate+tighten depend only on the shadow's tolerations and
        # scheduling requirements (post-topology-injection), so identical
        # pods — the bulk of any storm — share ONE evaluation instead of a
        # per-pod Requirements merge/consolidate pass (measured: ~1.3s of a
        # 10k-pod storm's drain was spent re-tightening 5 identical specs
        # 2000x each).
        _INCOMPATIBLE = object()
        evaluated: Dict[Tuple, object] = {}
        for pod, shadow in work:
            signature = (
                tuple(
                    (t.key, t.operator, t.value, t.effect)
                    for t in shadow.tolerations
                ),
                tuple(
                    (r.key, r.operator, tuple(r.values))
                    for r in shadow.scheduling_requirements()
                ),
            )
            entry = evaluated.get(signature)
            if entry is None:
                try:
                    constraints.validate_pod(shadow)
                except PodIncompatibleError:
                    # logged-and-skipped in the reference (scheduler.go:96)
                    evaluated[signature] = _INCOMPATIBLE
                    continue
                tightened = constraints.tighten(shadow)
                entry = (tightened, tightened.requirements.canonical_key())
                evaluated[signature] = entry
            elif entry is _INCOMPATIBLE:
                continue
            tightened, canonical = entry
            accelerators = frozenset(
                name
                for name in wellknown.ACCELERATOR_RESOURCES
                if pod.requests.get(name, 0) > 0
            )
            key = (canonical, accelerators)
            schedule = schedules.get(key)
            if schedule is None:
                schedule = Schedule(constraints=tightened)
                schedules[key] = schedule
                ordered.append(schedule)
            schedule.pods.append(pod)
        return ordered

    @staticmethod
    def _scheduling_copy(pod: PodSpec) -> PodSpec:
        import copy as _copy

        shadow = _copy.copy(pod)
        shadow.node_selector = dict(pod.node_selector)
        return shadow
