"""Scheduler: split a pod batch into groups of isomorphic constraints.

Ref: pkg/controllers/provisioning/scheduling/{scheduler,topology,
topologygroup}.go. The output Schedules feed the solver one at a time — all
pods in a Schedule are satisfiable by the same tightened constraint set, which
is what lets the solver treat them as one dense tensor problem.

Two topology regimes:

* **Compiled (default).** Topology-spread, pod (anti-)affinity, and the
  preference-relaxation ladder are NOT resolved here: the schedule carries
  its relaxation ladder (constraints/ladder.py) and the constraint compiler
  lowers everything into the [L, G, T] kernel dispatch at solve time
  (constraints/compiler.py). Spread pods that the greedy pre-pass used to
  split into one-schedule-per-zone stay in ONE schedule, so one dispatch
  co-optimizes spread against cost instead of serializing per domain.

* **Greedy (KARPENTER_GREEDY_TOPOLOGY=1 / Scheduler(greedy_topology=True)).**
  The legacy host-side pre-pass, kept as the parity oracle: topology-spread
  decisions are injected as node selectors ahead of the solve
  (Topology.inject, ref topology.go:40-140), now generalized to arbitrary
  topology keys and max_skew > 1 so the oracle covers everything the
  compiled path does (minus anti-affinity, which the pre-pass cannot
  express).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec, TopologySpreadConstraint
from karpenter_tpu.api.provisioner import Constraints, PodIncompatibleError, Provisioner
from karpenter_tpu.constraints.ladder import LadderState, RelaxationLadder, build_ladder
from karpenter_tpu.constraints.terms import node_domain, term_fingerprint
from karpenter_tpu.controllers.cluster import Cluster

# Legacy constant (the greedy pre-pass once rejected everything else); the
# compiled path and the generalized greedy fallback both take arbitrary
# node-label keys now, so this only names the keys with special lowering
# (hostname: fabricated domains / per-node caps).
SUPPORTED_TOPOLOGY_KEYS = (wellknown.HOSTNAME_LABEL, wellknown.ZONE_LABEL)

_domain_counter = itertools.count(1)


@dataclass
class Schedule:
    """Pods satisfiable by one tightened constraint set
    (ref: scheduler.go:54-58). On the compiled path a schedule additionally
    carries its relaxation ladder: `needs_compiler` schedules route through
    constraints/solve.solve_constrained (one [L, G, T] dispatch) instead of
    the plain solver boundary."""

    constraints: Constraints
    pods: List[PodSpec] = field(default_factory=list)
    ladder: Optional[RelaxationLadder] = None
    valid_levels: Optional[List[bool]] = None
    needs_compiler: bool = False
    # The constraint representative: the scheduler-local shadow whose
    # selector/spread/affinity state the compiler should read (on the
    # greedy-topology path the shadow carries injected selectors and has
    # its spread constraints cleared — inject already resolved them).
    # None = read pods[0].
    rep: Optional[PodSpec] = None


class TopologyGroup:
    """Greedy spread counter (ref: topologygroup.go:24-68)."""

    def __init__(self, constraint: TopologySpreadConstraint):
        self.constraint = constraint
        self.counts: Dict[str, int] = {}

    def register(self, *domains: str) -> None:
        for domain in domains:
            self.counts.setdefault(domain, 0)

    def increment(self, domain: str) -> None:
        if domain in self.counts:
            self.counts[domain] += 1

    def next_domain(self, allowed: Optional[Sequence[str]] = None) -> Optional[str]:
        """argmin-count domain within the pod's reachable set (mutating:
        increments the winner).

        Skew is measured against the floor of the REACHABLE domains — a
        pod whose selector excludes a domain cannot be asked to balance
        against it — and in that frame the argmin sequence never stretches
        skew beyond 1, so any max_skew >= 1 is honored without an explicit
        guard (a pod pinned to one over-full domain still lands there,
        exactly as the compiled water-fill fills a one-domain allowed set:
        constraints/compiler.water_fill_takes shares this frame, which is
        what keeps the two paths in placement parity). max_skew > 1 on the
        hostname key is realized upstream by bucket fabrication
        (_compute_hostname: ceil(n/max_skew) domains)."""
        candidates = [
            d for d in self.counts if allowed is None or d in allowed
        ]
        if not candidates:
            return None
        winner = min(candidates, key=lambda d: (self.counts[d], d))
        self.counts[winner] += 1
        return winner

    def assign_many(self, n: int) -> List[str]:
        """n sequential next_domain() picks, computed in closed form.

        The greedy loop is O(n x domains) Python — a real cost when a 50k-pod
        deployment carries one spread constraint. Observation: assigning a
        pod to domain d for the (j+1)-th time happens at "level" counts[d]+j,
        and greedy always takes the globally smallest (level, name); so the
        whole sequence is the first n slots of {(counts[d]+j, d)} in
        (level, name) order — water-filling + one lexsort, bit-identical to
        the sequential walk (the tensor-style reformulation of
        topologygroup.go:54-68's mutating argmin)."""
        if n <= 0 or not self.counts:
            return []
        names = sorted(self.counts)
        counts = np.array([self.counts[d] for d in names], dtype=np.int64)
        # Smallest water level L with sum(max(0, L - c_d)) >= n.
        lo, hi = int(counts.min()) + 1, int(counts.max()) + n
        while lo < hi:
            mid = (lo + hi) // 2
            if int(np.maximum(0, mid - counts).sum()) >= n:
                hi = mid
            else:
                lo = mid + 1
        level = lo
        full = np.maximum(0, (level - 1) - counts)  # slots strictly below L-1
        remaining = n - int(full.sum())
        takes = full.copy()
        # The last `remaining` picks happen at level L-1, in name order among
        # domains that have a slot there.
        for i in range(len(names)):
            if remaining == 0:
                break
            if counts[i] + full[i] == level - 1:  # next untaken slot is L-1
                takes[i] += 1
                remaining -= 1
        # Per-pod sequence: lexsort the taken slots by (level, name rank).
        domain_idx = np.repeat(np.arange(len(names)), takes)
        levels = np.concatenate(
            [np.arange(counts[i], counts[i] + takes[i]) for i in range(len(names))]
        )
        order = np.lexsort((domain_idx, levels))
        sequence = [names[i] for i in domain_idx[order]]
        for i, name in enumerate(names):
            self.counts[name] += int(takes[i])
        return sequence


class Topology:
    """Injects topology-spread decisions as node selectors
    (ref: topology.go:40-140) — the greedy fallback behind
    KARPENTER_GREEDY_TOPOLOGY, kept as the compiled path's parity oracle.
    Handles arbitrary topology keys: hostname fabricates fresh domains;
    every other key spreads over label values discovered from live nodes,
    the requirement envelope, and provisioner labels (matching the
    compiler's discover_domains)."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster

    def inject(self, constraints: Constraints, pods: Sequence[PodSpec]) -> None:
        for group_key, group_pods in self._topology_groups(pods).items():
            constraint = group_pods[0][0]
            group = TopologyGroup(constraint)
            members = [pod for _, pod in group_pods]
            if constraint.topology_key == wellknown.HOSTNAME_LABEL:
                self._compute_hostname(group, members)
            else:
                self._compute_labeled(group, constraints, members)
            allowed_per_pod = [
                self._allowed_domains_for_pod(pod, group) for pod in members
            ]
            if group.counts and all(a is None for a in allowed_per_pod):
                # Homogeneous fast path: no pod restricts its domains, so the
                # whole group's greedy sequence computes in closed form.
                for pod, domain in zip(members, group.assign_many(len(members))):
                    pod.node_selector[constraint.topology_key] = domain
                continue
            for pod, allowed in zip(members, allowed_per_pod):
                domain = group.next_domain(allowed)
                if domain is not None:
                    pod.node_selector[constraint.topology_key] = domain

    def _topology_groups(self, pods: Sequence[PodSpec]):
        """Group (constraint, pod) pairs by equivalent spread constraint
        (ref: topology.go:57-75). Arbitrary keys pass through: a key with no
        discoverable domains simply registers nothing and injects nothing."""
        groups: Dict[Tuple, List[Tuple[TopologySpreadConstraint, PodSpec]]] = {}
        for pod in pods:
            for constraint in pod.topology_spread:
                groups.setdefault(constraint.group_key(), []).append(
                    (constraint, pod)
                )
        return groups

    def _compute_hostname(self, group: TopologyGroup, pods: List[PodSpec]) -> None:
        """Fabricate ceil(pods/maxSkew) fresh hostname domains
        (ref: topology.go:95-105 — hostname domains don't exist until nodes
        launch, so the scheduler invents distinct buckets)."""
        num_domains = -(-len(pods) // max(group.constraint.max_skew, 1))
        for _ in range(num_domains):
            group.register(f"host-domain-{next(_domain_counter)}")

    def _compute_labeled(
        self, group: TopologyGroup, constraints: Constraints, pods: List[PodSpec]
    ) -> None:
        """Register allowed domains for an arbitrary label key and count
        existing matching pods per domain from live cluster state — the
        arbitrary-key generalization of the reference's zonal pass
        (ref: topology.go:112-140; zone stays a special case only in where
        a node's value is read from)."""
        key = group.constraint.topology_key
        allowed = constraints.effective_requirements().allowed(key)
        domains = set()
        for node in self.cluster.list_nodes():
            value = self._node_domain(node, key)
            if value and allowed.contains(value):
                domains.add(value)
        # Domains can also come from the constraint envelope (or provisioner
        # labels) even before any node exists there.
        finite = allowed.finite_values()
        if finite:
            domains |= set(finite)
        label_value = constraints.labels.get(key)
        if label_value and allowed.contains(label_value):
            domains.add(label_value)
        group.register(*sorted(domains))
        for pod in self.cluster.list_pods(
            predicate=lambda p: p.node_name is not None
            and group.constraint.matches(p.labels)
        ):
            node = self.cluster.try_get_node(pod.node_name)
            if node is None:
                continue
            value = self._node_domain(node, key)
            if value:
                group.increment(value)

    # THE zone-vs-label fallback rule, shared with the compiler's domain
    # discovery (constraints/terms.node_domain) so the greedy oracle and
    # the compiled path can never diverge on a node's domain.
    _node_domain = staticmethod(node_domain)

    def _allowed_domains_for_pod(self, pod: PodSpec, group: TopologyGroup):
        """A pod with its own zone/hostname selector restricts its domains."""
        key = group.constraint.topology_key
        selected = pod.node_selector.get(key)
        if selected is not None:
            return [selected]
        allowed = pod.scheduling_requirements().allowed(key)
        if allowed.is_any():
            return None
        return [d for d in group.counts if allowed.contains(d)]


class Scheduler:
    """Ref: scheduling/scheduler.go:67-126."""

    def __init__(self, cluster: Cluster, greedy_topology: Optional[bool] = None):
        self.cluster = cluster
        self.topology = Topology(cluster)
        if greedy_topology is None:
            from karpenter_tpu.constraints import greedy_topology_enabled

            greedy_topology = greedy_topology_enabled()
        self.greedy_topology = greedy_topology

    def solve(
        self, provisioner: Provisioner, pods: Sequence[PodSpec]
    ) -> List[Schedule]:
        constraints = provisioner.spec.constraints
        # Topology decisions (when the greedy oracle is active) are injected
        # into per-pass SHADOW copies, never the live pod: a fabricated
        # zone/hostname selector must not survive a failed launch, or
        # retries stay pinned to a blacked-out domain (the reference works
        # on scheduler-local pod copies too).
        work = [(pod, self._scheduling_copy(pod)) for pod in pods]
        if self.greedy_topology:
            # The parity oracle: spread resolves host-side ahead of the
            # solve, exactly like the reference's topology.go pre-pass. The
            # shadows then drop their spread constraints — inject already
            # turned them into selectors — while the relaxation ladder and
            # (rejected-at-selection) affinity still compile as usual.
            self.topology.inject(constraints, [shadow for _, shadow in work])
            for _, shadow in work:
                shadow.topology_spread = []
        return self._solve_compiled(constraints, work)

    # --- compiled path (default): constraints lower at solve time ----------

    @staticmethod
    def _compiled_signature(pod: PodSpec) -> Tuple:
        """Constraint-relevant identity of a pod: pods sharing it share one
        evaluation AND one schedule's ladder/spread/affinity config (the
        compiler reads a representative pod)."""
        return (
            tuple(
                (t.key, t.operator, t.value, t.effect) for t in pod.tolerations
            ),
            tuple(sorted(pod.node_selector.items())),
            tuple(
                (
                    term.weight,
                    tuple((r.key, r.operator, r.values) for r in term.requirements),
                )
                for term in pod.preferred_terms
            ),
            tuple(
                tuple((r.key, r.operator, r.values) for r in term)
                for term in pod.required_terms
            ),
            tuple(c.group_key() for c in pod.topology_spread),
            term_fingerprint(pod.pod_affinity_terms),
            term_fingerprint(pod.pod_anti_affinity_terms),
            # Labels join the signature ONLY when spread/affinity is in
            # play: the compiler reads the representative pod's labels
            # (hostname anti-affinity self-match, spread selector
            # membership), so label-divergent pods must not share a rep —
            # while plain pods keep merging regardless of labels.
            tuple(sorted(pod.labels.items()))
            if (
                pod.topology_spread
                or pod.pod_affinity_terms
                or pod.pod_anti_affinity_terms
            )
            else (),
        )

    @staticmethod
    def _level_shadow(pod: PodSpec, state: LadderState) -> PodSpec:
        import copy as _copy

        shadow = _copy.copy(pod)
        shadow.node_selector = dict(pod.node_selector)
        shadow.preferred_terms = list(state.preferred)
        shadow.required_terms = [list(term) for term in state.required]
        return shadow

    def _evaluate_compiled(self, constraints: Constraints, shadow: PodSpec):
        """One signature's evaluation over its shadow: (tightened, merge
        key, ladder, valid_levels, needs_compiler) or None when no
        relaxation level is compatible (the pod is skipped, as the legacy
        path skipped level-0-incompatible pods)."""
        ladder = build_ladder(shadow)
        needs = (
            ladder.num_levels > 1
            or bool(shadow.topology_spread)
            or bool(shadow.pod_affinity_terms)
            or bool(shadow.pod_anti_affinity_terms)
        )
        if not needs:
            # Plain pod: the legacy one-shot evaluation, bit-identical.
            try:
                constraints.validate_pod(shadow)
            except PodIncompatibleError:
                return None
            tightened = constraints.tighten(shadow)
            return (
                tightened,
                tightened.requirements.canonical_key(),
                None,
                None,
                False,
            )
        valid_levels = []
        for state in ladder.states:
            try:
                constraints.validate_pod(self._level_shadow(shadow, state))
                valid_levels.append(True)
            except PodIncompatibleError:
                valid_levels.append(False)
        if not any(valid_levels):
            return None
        # The schedule envelope is the WIDEST one — provisioner constraints
        # plus the pod's own selector, with no ladder terms: every level's
        # candidate types must survive the fleet filter, and each level's
        # mask narrows within it (constraints/compiler.py).
        base = self._scheduling_copy(shadow)
        base.preferred_terms = []
        base.required_terms = []
        tightened = constraints.tighten(base)
        return (
            tightened,
            tightened.requirements.canonical_key(),
            ladder,
            valid_levels,
            True,
        )

    def _solve_compiled(
        self, constraints: Constraints, work: Sequence[Tuple[PodSpec, PodSpec]]
    ) -> List[Schedule]:
        evaluated: Dict[Tuple, object] = {}
        _INCOMPATIBLE = object()
        schedules: Dict[Tuple, Schedule] = {}
        ordered: List[Schedule] = []
        for pod, shadow in work:
            signature = self._compiled_signature(shadow)
            entry = evaluated.get(signature)
            if entry is None:
                entry = (
                    self._evaluate_compiled(constraints, shadow) or _INCOMPATIBLE
                )
                evaluated[signature] = entry
            if entry is _INCOMPATIBLE:
                continue
            tightened, canonical, ladder, valid_levels, needs = entry
            accelerators = frozenset(
                name
                for name in wellknown.ACCELERATOR_RESOURCES
                if pod.requests.get(name, 0) > 0
            )
            # Compiled schedules merge by full signature (the compiler reads
            # a representative shadow, so members must be homogeneous);
            # plain schedules keep the legacy canonical-requirements merge.
            key = (signature, accelerators) if needs else (canonical, accelerators)
            schedule = schedules.get(key)
            if schedule is None:
                schedule = Schedule(
                    constraints=tightened,
                    ladder=ladder,
                    valid_levels=valid_levels,
                    needs_compiler=needs,
                    rep=shadow,
                )
                schedules[key] = schedule
                ordered.append(schedule)
            schedule.pods.append(pod)
        return ordered

    @staticmethod
    def _scheduling_copy(pod: PodSpec) -> PodSpec:
        import copy as _copy

        shadow = _copy.copy(pod)
        shadow.node_selector = dict(pod.node_selector)
        return shadow
