"""Shared node-disruption eligibility predicates.

Emptiness TTL deletion (controllers/node.py) and consolidation
(controllers/consolidation.py) are both VOLUNTARY disruption paths — they
choose to remove capacity that could keep running. Before this module each
carried its own copy of "may I touch this node", and the copies could
disagree: a node stamped with the emptiness timestamp could concurrently be
nominated for a consolidation replace, double-disrupting it. The predicates
live here exactly once; both controllers import them, so they cannot drift.
"""

from __future__ import annotations

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.cloudprovider import NodeSpec
from karpenter_tpu.controllers.cluster import Cluster


def is_workload_pod(pod: PodSpec) -> bool:
    """Counts against emptiness / consolidation headroom: a live pod not
    bound to the node by ownership (daemon/static pods die with the node)
    and not already on its way out (ref: emptiness.go isEmpty:84)."""
    return not (
        pod.is_terminal()
        or pod.is_terminating()
        or pod.is_owned_by_daemonset()
        or pod.is_owned_by_node()
    )


def is_empty(cluster: Cluster, node: NodeSpec) -> bool:
    """Empty = no workload pods (only daemons/static/terminating remain)."""
    for pod in cluster.list_pods(node_name=node.name):
        if is_workload_pod(pod):
            return False
    return True


def voluntary_disruption_allowed(node: NodeSpec) -> bool:
    """A node may be voluntarily disrupted only when no other lifecycle owns
    it: it has joined (ready), is not already deleting (the finalizer path
    owns it), and carries no interruption notice (the reclamation drain owns
    it — voluntary cost actions must never fight the deadline-driven one)."""
    return (
        node.ready
        and node.deletion_timestamp is None
        and wellknown.INTERRUPTION_KIND_ANNOTATION not in node.annotations
    )


def emptiness_owns(provisioner, node: NodeSpec) -> bool:
    """True when the emptiness TTL path has claimed this node (the TTL is
    configured and the timestamp is stamped): its deletion is already
    scheduled, so consolidation must not concurrently nominate it."""
    return (
        provisioner is not None
        and provisioner.spec.ttl_seconds_after_empty is not None
        and wellknown.EMPTINESS_TIMESTAMP_ANNOTATION in node.annotations
    )
