"""Shared node-disruption eligibility predicates and the disruption ledger.

Emptiness TTL deletion (controllers/node.py), consolidation
(controllers/consolidation.py), drift replacement (controllers/drift.py) and
expiration (rewired through drift as kind "expired") are all VOLUNTARY
disruption paths — they choose to remove capacity that could keep running.
Before this module each carried its own copy of "may I touch this node", and
the copies could disagree: a node stamped with the emptiness timestamp could
concurrently be nominated for a consolidation replace, double-disrupting it.
The predicates live here exactly once; every voluntary actor imports them,
so they cannot drift.

The `DisruptionLedger` generalizes the per-controller budgets into ONE
fleet-wide voluntary-disruption budget (`--disruption-budget`): every
voluntary actor asks the ledger for headroom before claiming a victim, and
every in-flight claim — whichever controller stamped it — counts against the
shared total until the victim is gone. Per-reason caps (consolidation's
`--consolidation-max-disruption`, drift's `--drift-max-disruption`) nest
inside the global budget; the effective headroom for a reason is
min(global remaining, reason cap remaining). The ledger holds no state of
its own: claims are read from the durable node annotations on every call,
so a restarted controller sees exactly the same budget a continuous one
would, and two actors sharing one cluster can never overspend by more than
their sweep interleaving (each claim is re-counted before the next grant).
"""

from __future__ import annotations

from typing import Dict, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.cloudprovider import NodeSpec
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.utils.metrics import REGISTRY

# Fleet-wide cap on concurrently in-flight voluntary disruptions
# (`--disruption-budget`). 0 disables ALL voluntary disruption.
DEFAULT_DISRUPTION_BUDGET = 10

REASON_CONSOLIDATION = "consolidation"
REASON_DRIFT = "drift"
REASON_EMPTINESS = "emptiness"

DISRUPTION_BUDGET_IN_USE = REGISTRY.gauge(
    "disruption_budget_in_use",
    "Voluntary disruptions currently in flight across every reason "
    "(consolidation + drift/expiration + emptiness), as last counted by a "
    "ledger headroom check",
)


def is_workload_pod(pod: PodSpec) -> bool:
    """Counts against emptiness / consolidation headroom: a live pod not
    bound to the node by ownership (daemon/static pods die with the node)
    and not already on its way out (ref: emptiness.go isEmpty:84)."""
    return not (
        pod.is_terminal()
        or pod.is_terminating()
        or pod.is_owned_by_daemonset()
        or pod.is_owned_by_node()
    )


def is_empty(cluster: Cluster, node: NodeSpec) -> bool:
    """Empty = no workload pods (only daemons/static/terminating remain)."""
    for pod in cluster.list_pods(node_name=node.name):
        if is_workload_pod(pod):
            return False
    return True


def voluntary_disruption_allowed(node: NodeSpec) -> bool:
    """A node may be voluntarily disrupted only when no other lifecycle owns
    it: it has joined (ready), is not already deleting (the finalizer path
    owns it), and carries no interruption notice (the reclamation drain owns
    it — voluntary cost actions must never fight the deadline-driven one)."""
    return (
        node.ready
        and node.deletion_timestamp is None
        and wellknown.INTERRUPTION_KIND_ANNOTATION not in node.annotations
    )


def emptiness_owns(provisioner, node: NodeSpec) -> bool:
    """True when the emptiness TTL path has claimed this node (the TTL is
    configured and the timestamp is stamped): its deletion is already
    scheduled, so consolidation must not concurrently nominate it."""
    return (
        provisioner is not None
        and provisioner.spec.ttl_seconds_after_empty is not None
        and wellknown.EMPTINESS_TIMESTAMP_ANNOTATION in node.annotations
    )


def claim_reason(node: NodeSpec) -> Optional[str]:
    """Which voluntary-disruption reason currently owns this node, or None.

    Consolidation and drift claims are the durable action annotations —
    present from the moment of nomination until the finalizer removes the
    node, so a victim counts against the budget through its whole drain.
    An emptiness claim counts only once DELETION has begun: the timestamp
    annotation alone is a scheduled intent (an idle cluster can carry dozens
    of empty nodes waiting out their TTL, and those must not starve
    consolidation/drift of the shared budget — they are not disrupting
    anything yet)."""
    if wellknown.CONSOLIDATION_ACTION_ANNOTATION in node.annotations:
        return REASON_CONSOLIDATION
    if wellknown.DRIFT_ACTION_ANNOTATION in node.annotations:
        return REASON_DRIFT
    if (
        wellknown.EMPTINESS_TIMESTAMP_ANNOTATION in node.annotations
        and node.deletion_timestamp is not None
    ):
        return REASON_EMPTINESS
    return None


class DisruptionLedger:
    """The shared voluntary-disruption budget (module docstring).

    Stateless over the store: `in_flight()` re-derives the claim counts from
    the durable node annotations on every call, so the ledger needs no
    persistence, no cross-controller locking, and survives restarts for
    free. `reason_caps` maps reason -> per-reason concurrent cap (a missing
    reason is bounded only by the global budget; a cap of 0 disables that
    reason entirely)."""

    def __init__(
        self,
        cluster: Cluster,
        budget: int = DEFAULT_DISRUPTION_BUDGET,
        reason_caps: Optional[Dict[str, int]] = None,
    ):
        self.cluster = cluster
        self.budget = budget
        self.reason_caps = dict(reason_caps or {})

    def in_flight(self) -> Dict[str, int]:
        """Live claim count per reason, freshly derived from the store."""
        counts = {
            REASON_CONSOLIDATION: 0,
            REASON_DRIFT: 0,
            REASON_EMPTINESS: 0,
        }
        for node in self.cluster.list_nodes():
            reason = claim_reason(node)
            if reason is not None:
                counts[reason] += 1
        return counts

    def headroom(self, reason: str) -> int:
        """How many NEW victims `reason` may claim right now:
        min(global budget remaining, reason cap remaining), floored at 0."""
        counts = self.in_flight()
        total = sum(counts.values())
        DISRUPTION_BUDGET_IN_USE.set(float(total))
        room = self.budget - total
        cap = self.reason_caps.get(reason)
        if cap is not None:
            room = min(room, cap - counts.get(reason, 0))
        return max(0, room)
