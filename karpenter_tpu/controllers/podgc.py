"""Orphaned-pod garbage collection.

Ref: the reference leans on kube-controller-manager's podgc
(`gcOrphaned`) to delete pods bound to nodes that no longer exist — a bind
can land on a node concurrently being drained+deleted (the provisioner's
bind fan-out racing the termination controller), and nothing else ever
revisits such a pod: its node key no longer reconciles and the pod itself
is not unschedulable. Since this framework replaces the surrounding
cluster, it must carry the reaper itself.

Deletion requires TWO consecutive sightings of the same orphan (one sweep
interval apart): a single observation can be a transient watch-ordering
window where the pod's binding event arrived before the node's ADDED event.
"""

from __future__ import annotations

from typing import Set, Tuple

from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.metrics import REGISTRY

log = klog.named("podgc")

SWEEP_SECONDS = 10.0

PODGC_DELETED_TOTAL = REGISTRY.counter(
    "podgc_deleted_total", "Orphaned pods reaped (bound to a vanished node)"
)
PODGC_SUSPECTS = REGISTRY.gauge(
    "podgc_suspect_count", "Orphan candidates awaiting a second sighting"
)


class PodGcController:
    """Periodic sweep (Manager drives it like the metrics poll): delete
    bound, non-terminating pods whose node vanished."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        # Keyed by (namespace, name, uid): a name reused by a NEW pod
        # incarnation must restart the two-sighting clock — matching a new
        # pod against an old incarnation's suspicion would delete a live
        # pod on its first sighting (kube-controller-manager's gcOrphaned
        # likewise operates on UIDs).
        self._suspects: Set[Tuple[str, str, str]] = set()

    def reconcile(self, _key=None) -> float:
        node_names = {node.name for node in self.cluster.list_nodes()}
        orphans: Set[Tuple[str, str, str]] = set()
        for pod in self.cluster.list_pods():
            # Terminating pods are orphans too: with the node gone there is
            # no kubelet left to complete the eviction, so the pod would
            # stay terminating forever (kube's gcOrphaned force-deletes the
            # same way). The two-sighting rule still applies.
            if pod.node_name is not None and pod.node_name not in node_names:
                orphans.add((pod.namespace, pod.name, getattr(pod, "uid", "") or ""))
        deleted: Set[Tuple[str, str, str]] = set()
        for key in orphans & self._suspects:  # second consecutive sighting
            namespace, name, uid = key
            try:
                # UID-preconditioned: a same-name pod re-created (and bound to
                # a live node) between this sweep's listing and the delete must
                # survive — kube-controller-manager's gcOrphaned does the same.
                removed = self.cluster.delete_pod(namespace, name, uid=uid or None)
                deleted.add(key)  # observed incarnation is gone either way
                if removed:
                    PODGC_DELETED_TOTAL.inc()
                    log.info(
                        "deleted orphaned pod %s/%s (node gone)", namespace, name
                    )
            except Exception:  # noqa: BLE001 — transient failure or raced
                # deletion: STAY a suspect so the very next sweep retries.
                log.debug("orphan %s/%s delete failed; retrying", namespace, name)
        self._suspects = orphans - deleted
        PODGC_SUSPECTS.set(len(self._suspects))
        return SWEEP_SECONDS
