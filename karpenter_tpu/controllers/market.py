"""Market sweep: fold provider price/ICE ticks and re-solve on reprice.

The reference requeues every provisioner on a 5-minute timer purely to pick
up instance-type/pricing drift (SURVEY.md §2.2, provisioning/controller.go
:80). This controller is the dynamic analogue: it polls the provider's
market feed (``CloudProvider.poll_market_events`` — DescribeSpotPriceHistory
-shaped on EC2, a seeded replayable walk on the fake), folds ticks into the
generation-tagged PriceBook, and when a pool's price drifts past
``--reprice-threshold`` it requeues provisioning and consolidation NOW —
debounced per pool, so a price storm costs at most one re-solve per pool per
``--reprice-debounce`` window and a sub-threshold storm costs none.

Chaos legs:

- ``market.feed`` faultpoint (stale | reorder | blackout): the feed's
  partial-failure modes. Reordered batches are absorbed by the seq-sorted
  fold; stale polls hold back the newest ticks (they redeliver next sweep);
  a blackout skips the poll entirely and shows up as
  ``market_feed_staleness_seconds`` climbing.
- ``market.mid-tick`` crashpoint between folded ticks: a controller killed
  mid-fold restarts, re-polls from seq 0, and reconstructs the identical
  book state AND generation (the fold is an idempotent pure function of the
  tick sequence — tests/test_market_feed.py, on both store backends).

Every generation bump lands in the flight recorder as a ``reprice`` event
(pool, old/new discount, generation, affected controllers), and launches
stamp the generation they were priced under (controllers/provisioning.py) —
a breach dump names the market state each purchase was made against.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.cloudprovider import CloudProvider
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.market.pricebook import PriceBook, Reprice
from karpenter_tpu.utils import faultpoints
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.crashpoints import crashpoint
from karpenter_tpu.utils.metrics import REGISTRY
from karpenter_tpu.utils.obs import RECORDER

SWEEP_SECONDS = 1.0
DEFAULT_DEBOUNCE_SECONDS = 5.0
OD_CACHE_TTL_SECONDS = 60.0

MARKET_PRICE_DOLLARS = REGISTRY.gauge(
    "market_price_dollars",
    "Advertised spot $/hr per pool as the controller's PriceBook folds the "
    "feed (pool_kind = instance-type/zone)",
    ["pool_kind"],
)
MARKET_REPRICE_TOTAL = REGISTRY.counter(
    "market_reprice_total",
    "PriceBook generation bumps by reason (price-delta | ice); each one "
    "invalidates the compiled-envelope and fleet caches and requeues the "
    "cost controllers (debounced per pool)",
    ["reason"],
)
MARKET_FEED_STALENESS = REGISTRY.gauge(
    "market_feed_staleness_seconds",
    "Feed-time age of the newest applied market tick — a climbing value "
    "means the feed is blacked out or the provider stopped publishing",
)
FORECAST_RISK_SCORE = REGISTRY.gauge(
    "forecast_risk_score",
    "Quantized interruption-risk forecast per pool (depth-decline trend + "
    "recent interruptions; the per-[T] packing penalty derives from this)",
    ["pool_kind"],
)


def _pool_kind(instance_type: str, zone: str) -> str:
    return f"{instance_type}/{zone}"


class MarketController:
    """Periodic sweep (Manager drives it like interruption/consolidation):
    poll the feed, fold ticks, publish market metrics, requeue cost
    decisions on debounced reprices."""

    def __init__(
        self,
        cluster: Cluster,
        cloud: CloudProvider,
        book: PriceBook,
        debounce_seconds: float = DEFAULT_DEBOUNCE_SECONDS,
        sweep_seconds: float = SWEEP_SECONDS,
    ):
        self.cluster = cluster
        self.cloud = cloud
        self.book = book
        self.debounce_seconds = debounce_seconds
        # Poll cadence: 1s suits the fake's in-memory feed; EC2 deployments
        # should pace this to the API (--market-poll-interval, default 15s
        # there) — every sweep is a paginated DescribeSpotPriceHistory.
        self.sweep_seconds = sweep_seconds
        self.log = klog.named("market")
        # Set by the runtime (Manager._reprice_requeue): enqueues every
        # provisioner plus a consolidation sweep. None in unit harnesses.
        self.requeue = None
        # Reprices awaiting their debounce window, and when each pool last
        # triggered a requeue. Only the single market sweep key touches
        # these (concurrency=1, key collapse-deduped), so no lock.
        self._pending: Dict[tuple, str] = {}
        self._last_requeue: Dict[tuple, float] = {}
        self._od_cache: Optional[Dict[tuple, float]] = None
        self._od_cache_at = float("-inf")
        self._od_no_anchor: set = set()

    # --- sweep --------------------------------------------------------------

    def reconcile(self, _key=None) -> float:
        ticks = self._poll()
        reprices = self._fold(ticks)
        self._publish(ticks, reprices)
        self._requeue_due(reprices)
        return self.sweep_seconds

    def _poll(self) -> List:
        fault = faultpoints.draw("market.feed")
        if fault is not None and fault.kind == "blackout":
            # The feed went dark: nothing delivered this sweep; staleness
            # climbs until the blackout lifts (nothing to retry — the next
            # poll re-reads the full history past the cursor).
            MARKET_FEED_STALENESS.set(self.book.staleness_s())
            return []
        ticks = list(self.cloud.poll_market_events(self.book.last_seq))
        if fault is not None and fault.kind == "stale":
            # The provider served a stale snapshot: the newest half of the
            # batch is missing. Those ticks redeliver next sweep (the
            # cursor only advances past what was folded).
            ticks = ticks[: len(ticks) // 2]
        elif fault is not None and fault.kind == "reorder":
            ticks = list(reversed(ticks))
        return ticks

    def _fold(self, ticks: List) -> List[Reprice]:
        reprices: List[Reprice] = []
        # The fold is seq-ordered regardless of delivery order (the reorder
        # fault above, a racy provider): sorting restores the canonical
        # sequence, and the book's seq high-water mark makes replays no-ops.
        for tick in sorted(ticks, key=lambda t: t.seq):
            reprice = self.book.apply(tick)
            if reprice is not None:
                reprices.append(reprice)
                MARKET_REPRICE_TOTAL.inc(reprice.reason)
                RECORDER.record(
                    "reprice",
                    pool=_pool_kind(*reprice.pool),
                    reason=reprice.reason,
                    old_discount=reprice.old_discount,
                    new_discount=reprice.new_discount,
                    generation=reprice.generation,
                    affected="provisioning,consolidation",
                )
            # A kill between folded ticks: the restart re-polls from seq 0
            # and re-folds to the identical state + generation.
            crashpoint("market.mid-tick")
        return reprices

    def _publish(self, ticks: List, reprices: List[Reprice]) -> None:
        MARKET_FEED_STALENESS.set(self.book.staleness_s())
        # Risk publishes for EVERY book pool, every sweep, through the
        # REQUANTIZING read: the dominant hazard input (note_interruption,
        # from the interruption controller) moves risk on pools that may
        # never tick again, and its decay must reach both this gauge AND
        # the fleet-cache fingerprint (risk_generation bumps on any quantum
        # crossing) — the runbook tells operators to judge launches by this
        # gauge, so it must track what the packer actually pays.
        for pool, risk in self.book.requantized_risks().items():
            FORECAST_RISK_SCORE.set(risk, _pool_kind(*pool))
        if not ticks:
            return
        touched = {tick.pool for tick in ticks}
        od_prices = self._od_prices(touched)
        for pool in touched:
            if self.book.is_closed(pool):
                # The pool advertises NO spot offering while ICE-closed —
                # a retained gauge row would show a live, purchasable-
                # looking price for an unbuyable pool. Drop the series;
                # the reopen tick republishes it.
                kind = _pool_kind(*pool)
                MARKET_PRICE_DOLLARS.remove_where(
                    lambda values: values == (kind,)
                )
                continue
            discount = self.book.spot_discount(pool)
            od = od_prices.get(pool)
            if discount is not None and od is not None:
                MARKET_PRICE_DOLLARS.set(od * discount, _pool_kind(*pool))

    def _od_prices(self, needed: set) -> Dict[tuple, float]:
        """On-demand anchor map for the price gauge, cached: rebuilding the
        full provider catalog (get_instance_types routes every spot offering
        through the repricing rule) every ticking sweep just to read static
        anchors would make the gauge the most expensive part of the sweep.
        Refreshes when a genuinely NEW pool is missing (new type/zone) or
        the cache passes its TTL (anchors move only on catalog changes);
        pools known to have no on-demand anchor — spot-only zones are a
        supported shape — are remembered so they cannot re-trigger the
        rebuild on every ticking sweep."""
        now = self.cluster.clock.now()
        if (
            self._od_cache is None
            or now - self._od_cache_at >= OD_CACHE_TTL_SECONDS
            or any(
                pool not in self._od_cache and pool not in self._od_no_anchor
                for pool in needed
            )
        ):
            out: Dict[tuple, float] = {}
            for it in self.cloud.get_instance_types():
                for offering in it.offerings:
                    if offering.capacity_type == wellknown.CAPACITY_TYPE_ON_DEMAND:
                        out[(it.name, offering.zone)] = offering.price
            self._od_cache = out
            self._od_cache_at = now
            self._od_no_anchor = {p for p in needed if p not in out}
        return self._od_cache

    def _requeue_due(self, reprices: List[Reprice]) -> None:
        """Per-pool debounce: a repricing pool requeues the cost controllers
        at most once per window; bumps inside the window coalesce into the
        pending set (the eventual requeue reads the latest book anyway).
        Sub-threshold storms never reach here at all — no reprice, no
        requeue, the sweep cadence is untouched."""
        for reprice in reprices:
            self._pending[reprice.pool] = reprice.reason
        if not self._pending:
            return
        now = self.cluster.clock.now()
        due = [
            pool
            for pool in self._pending
            if now - self._last_requeue.get(pool, float("-inf"))
            >= self.debounce_seconds
        ]
        if not due:
            return
        for pool in due:
            self._last_requeue[pool] = now
            del self._pending[pool]
        self.log.info(
            "market repriced %d pool(s) (generation %d): requeueing "
            "provisioning + consolidation",
            len(due),
            self.book.generation,
        )
        if self.requeue is not None:
            self.requeue()
