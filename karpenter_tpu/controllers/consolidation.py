"""Consolidation: cost-driven deprovisioning by cluster re-solve.

Everything before this controller only solves for PENDING pods — nodes are
bought, then removed only when empty, expired, or dead, so cost drifts
upward as workloads churn (BENCH_r05 steady-state cost_ratio 0.64, per-seed
lows of 0.51). This subsystem closes the loop from observed cluster state
back through the batched solver to a deprovisioning decision, the way
modern Karpenter's consolidation does — except the counterfactuals for ALL
candidate nodes are scored in one batched dispatch (ops/consolidate.py)
instead of being simulated one at a time:

1. **Nominate.** Underutilized-by-requested-resources nodes that nothing
   else owns: cordon-free, ready, not deleting, no interruption notice, not
   claimed by the emptiness TTL (the shared predicates in
   controllers/eligibility.py), current offering marked `consolidatable`,
   and every replaceable pod PDB-drainable right now
   (`PodSpec.survives_node_drain()` + the cluster's PDB gate).

2. **Batch-evaluate.** One `ops.consolidate.solve_candidates` dispatch per
   sweep scores, for every candidate simultaneously, "delete the node and
   repack its pods onto remaining headroom" and "replace the node with a
   strictly cheaper instance type", with per-candidate masking carrying the
   envelope differences. Savings are $/hr at the current offering prices.

3. **Execute** the best cost-positive action(s) — at most
   `--consolidation-max-disruption` (default 1) per sweep — through the
   PR 3 drain path: stamp the action annotation (durable intent), cordon,
   PDB-gated `reschedule_pod` displacement (bumping the reschedule epoch so
   any replacement launch never aliases the dying node's purchase), then
   finalizer-path delete. Delete-action pods are rebound straight onto
   their planned receivers (this store has no kube-scheduler to do it);
   replace-action pods are fed to `ProvisionerWorker.add`, so replacement
   capacity is launching BEFORE the victim finishes draining. Consolidation
   is strictly voluntary: it never overrides PDBs or do-not-evict — a
   protection appearing mid-drain cancels the action.

Consolidation yields to reclamation: any interruption notice or a foreign
node deletion suppresses sweeps for `--consolidation-cooldown` seconds past
the last observed activity, so the voluntary path never fights the
deadline-driven one.

Crash consistency: `consolidation.{after-nominate,mid-drain,before-delete}`
are named crashpoints; the battletest (tests/test_consolidation.py,
`make consolidation-smoke`) kills the controller at each and asserts a
restart converges — pods bound exactly once, victim gone, zero leaks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import PodIncompatibleError
from karpenter_tpu.api.taints import taints_tolerate_pod
from karpenter_tpu.cloudprovider import CloudProvider, NodeSpec, Offering
from karpenter_tpu.controllers import eligibility
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.controllers.errors import PDBViolationError
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.controllers.termination import TerminationController
from karpenter_tpu.ops import consolidate
from karpenter_tpu.ops.encode import (
    InstanceFleet,
    PodGroups,
    build_fleet,
    group_pods,
    resource_vector,
)
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.crashpoints import crashpoint
from karpenter_tpu.utils.metrics import REGISTRY

SWEEP_SECONDS = 10.0
# Voluntary disruption waits this long after any interruption/termination
# activity so consolidation never fights the reclamation path.
DEFAULT_COOLDOWN_SECONDS = 60.0
DEFAULT_MAX_DISRUPTION = 1
# A node is nominated when its requested-resources utilization (max over
# tracked dims) sits below this fraction — fuller nodes have nothing worth
# shedding: delete can't repack them and a strictly cheaper type can't hold
# their demand.
UNDERUTILIZED_FRACTION = 0.85
# Candidate cap per sweep: the batched solve is cheap but the nomination
# walk is O(nodes x pods); the lowest-utilization slice carries the wins.
MAX_CANDIDATES = 64

ACTION_DELETE = "delete"
ACTION_REPLACE = "replace"

CONSOLIDATION_ACTIONS_TOTAL = REGISTRY.counter(
    "consolidation_actions_total",
    "Consolidation actions by kind and outcome "
    "(executed|blocked|cancelled)",
    ["action", "result"],
)
CONSOLIDATION_SAVINGS_TOTAL = REGISTRY.counter(
    "consolidation_savings_dollars_total",
    "Projected $/hr shed by executed consolidation actions (accumulates "
    "the per-action savings estimate)",
)
CONSOLIDATION_CANDIDATES = REGISTRY.gauge(
    "consolidation_candidate_count",
    "Nodes nominated for counterfactual evaluation in the last sweep",
)


@dataclass
class Candidate:
    node: NodeSpec
    provisioner_name: str
    pods: List[PodSpec]  # replaceable (survives_node_drain) pods
    groups: PodGroups
    price: float  # current offering $/hr
    utilization: float
    constrained: bool  # pods carry node-level scheduling requirements


@dataclass
class Action:
    node_name: str
    kind: str  # ACTION_DELETE | ACTION_REPLACE
    savings: float
    # Delete only: pod uid -> planned receiver node name. Best-effort — a
    # receiver that changed since the solve falls back to the provisioner.
    assignment: Optional[Dict[str, str]] = None


class ConsolidationController:
    """Periodic sweep (Manager drives it like instancegc/interruption):
    nominate, batch-evaluate, execute at most the disruption budget."""

    def __init__(
        self,
        cluster: Cluster,
        cloud: CloudProvider,
        provisioning: ProvisioningController,
        termination: TerminationController,
        max_disruption: int = DEFAULT_MAX_DISRUPTION,
        cooldown_seconds: float = DEFAULT_COOLDOWN_SECONDS,
        cluster_state=None,
        ledger: Optional[eligibility.DisruptionLedger] = None,
    ):
        self.cluster = cluster
        self.cloud = cloud
        self.provisioning = provisioning
        self.termination = termination
        self.max_disruption = max_disruption
        self.cooldown_seconds = cooldown_seconds
        # The shared voluntary-disruption budget. The Manager passes one
        # ledger spanning every voluntary actor; directly-constructed
        # controllers (tests) get a private ledger whose consolidation cap
        # is max_disruption — the pre-ledger budget semantics.
        self.ledger = ledger or eligibility.DisruptionLedger(
            cluster,
            reason_caps={eligibility.REASON_CONSOLIDATION: max_disruption},
        )
        # Incremental encoder (models/cluster_state.DeviceClusterState):
        # nomination and receiver scoring read its O(delta)-maintained
        # per-node pod index and used vectors instead of re-listing every
        # pod per node per sweep (O(nodes x pods) on the snapshot path).
        # EXECUTION (drain / rebind) stays on the authoritative store.
        self.cluster_state = cluster_state
        self.log = klog.named("consolidation")
        # In-memory accounting only: the ACTION ANNOTATION on the victim is
        # the durable intent a restart resumes from. Savings estimates are
        # best-effort across a restart (delete recomputes from the current
        # offering price; a resumed replace records 0).
        self._savings: Dict[str, float] = {}
        self._last_reclamation: Optional[float] = None

    # --- sweep --------------------------------------------------------------

    def reconcile(self, _key=None) -> float:
        if self.max_disruption <= 0:
            return SWEEP_SECONDS  # consolidation disabled
        # Resume in-flight drains first (a restarted controller finds the
        # durable action annotation; the per-pod plan is recomputable but
        # not stored — resumed displacements route through the provisioner).
        for node in self._claimed_nodes():
            if node.deletion_timestamp is None:
                self._drain(node, assignment=None)
        if self._reclamation_cooldown():
            return SWEEP_SECONDS
        budget = self.ledger.headroom(eligibility.REASON_CONSOLIDATION)
        if budget <= 0:
            return SWEEP_SECONDS
        candidates = self._nominate()
        CONSOLIDATION_CANDIDATES.set(float(len(candidates)))
        if not candidates:
            return SWEEP_SECONDS
        for action in self._evaluate(candidates)[:budget]:
            self._begin(action)
        return SWEEP_SECONDS

    def _claimed_nodes(self) -> List[NodeSpec]:
        """Nodes carrying the consolidation action annotation — in-flight
        victims, whether still draining or already on the finalizer path.
        All of them count against the disruption budget until gone."""
        return [
            node
            for node in self.cluster.list_nodes()
            if wellknown.CONSOLIDATION_ACTION_ANNOTATION in node.annotations
        ]

    def _reclamation_cooldown(self) -> bool:
        """True while interruption/termination activity is live or cooled
        down less than `cooldown_seconds` ago. Our own victims (deleting
        WITH the consolidation annotation) don't arm the cooldown — they are
        paced by the in-flight budget instead."""
        now = self.cluster.clock.now()
        for node in self.cluster.list_nodes():
            foreign_delete = (
                node.deletion_timestamp is not None
                and wellknown.CONSOLIDATION_ACTION_ANNOTATION
                not in node.annotations
            )
            if (
                foreign_delete
                or wellknown.INTERRUPTION_KIND_ANNOTATION in node.annotations
            ):
                self._last_reclamation = now
                return True
        return (
            self._last_reclamation is not None
            and now - self._last_reclamation < self.cooldown_seconds
        )

    # --- nomination ----------------------------------------------------------

    def _nominate(self) -> List[Candidate]:
        catalog = {it.name: it for it in self.cloud.get_instance_types()}
        candidates: List[Candidate] = []
        for node in self.cluster.list_nodes():
            candidate = self._nominate_one(node, catalog)
            if candidate is not None:
                candidates.append(candidate)
        candidates.sort(key=lambda c: (c.utilization, c.node.name))
        return candidates[:MAX_CANDIDATES]

    def _pods_on(self, name: str) -> List[PodSpec]:
        """One node's pods: the incremental index (O(pods on the node))
        when the state is wired, the full-store filter otherwise."""
        if self.cluster_state is not None:
            return self.cluster_state.pods_on_node(name)
        return self.cluster.list_pods(node_name=name)

    def _used_on(self, name: str) -> Optional[np.ndarray]:
        """One node's summed non-terminal request vector from the
        incremental state, or None to compute from a pod walk."""
        if self.cluster_state is not None:
            return self.cluster_state.node_used(name)
        return None

    def _nominate_one(self, node: NodeSpec, catalog) -> Optional[Candidate]:
        provisioner_name = self._owned_and_free(node)
        if provisioner_name is None:
            return None
        offering = self._offering(node, catalog)
        if offering is None or not offering.consolidatable or offering.price <= 0:
            return None
        pods = self._pods_on(node.name)
        replaceable = self._drainable_pods(pods)
        if replaceable is None:
            return None
        utilization = self._utilization(node, pods, catalog)
        if utilization >= UNDERUTILIZED_FRACTION:
            return None
        constrained = any(
            p.node_selector
            or p.required_terms
            or p.topology_spread
            # Pod (anti-)affinity is admitted by selection now (the
            # constraint compiler lowers it); the counterfactual re-solve
            # here does not, so such pods mark the candidate constrained.
            or p.pod_affinity_terms
            or p.pod_anti_affinity_terms
            for p in replaceable
        )
        return Candidate(
            node=node,
            provisioner_name=provisioner_name,
            pods=replaceable,
            groups=group_pods(replaceable),
            price=offering.price,
            utilization=utilization,
            constrained=constrained,
        )

    def _owned_and_free(self, node: NodeSpec) -> Optional[str]:
        """The owning provisioner's name iff the node is ours and no other
        lifecycle has a claim on it (shared voluntary-disruption gate +
        the emptiness-TTL claim from controllers/eligibility.py)."""
        provisioner_name = node.labels.get(wellknown.PROVISIONER_NAME_LABEL)
        if provisioner_name is None:
            return None  # not ours
        provisioner = self.cluster.try_get_provisioner(provisioner_name)
        if provisioner is None:
            return None
        if node.unschedulable:
            return None  # cordoned (by an operator or an in-flight drain)
        if eligibility.claim_reason(node) is not None:
            return None  # in flight already (ours, drift's, or emptiness's)
        if not eligibility.voluntary_disruption_allowed(node):
            return None
        if eligibility.emptiness_owns(provisioner, node):
            return None  # the emptiness TTL path has claimed it
        return provisioner_name

    def _drainable_pods(self, pods: List[PodSpec]) -> Optional[List[PodSpec]]:
        """The replaceable subset of one node's (already listed) pods iff
        every one of them may be displaced right now (no protections, PDB
        budgets all allow it); None marks the node un-nominatable this
        sweep."""
        replaceable = [p for p in pods if p.survives_node_drain()]
        if not replaceable:
            return None  # empty — emptiness's job, not a cost action
        if any(
            wellknown.DO_NOT_EVICT_ANNOTATION in p.annotations
            for p in replaceable
        ):
            return None  # voluntary disruption never overrides protections
        if any(not self.cluster._pdb_allows(p) for p in replaceable):
            return None  # not PDB-drainable right now
        return replaceable

    @staticmethod
    def _offering(node: NodeSpec, catalog) -> Optional[Offering]:
        instance_type = catalog.get(node.instance_type)
        if instance_type is None:
            return None  # unknown or fully blacked-out type: leave it alone
        for offering in instance_type.offerings:
            if (
                offering.zone == node.zone
                and offering.capacity_type == node.capacity_type
            ):
                return offering
        return None

    @staticmethod
    def _pod_vector(pod: PodSpec) -> np.ndarray:
        cached = getattr(pod, "dense_vector", None)
        if cached is not None:
            return cached[0]
        return resource_vector(pod.requests)

    def _usable_capacity(self, node: NodeSpec, catalog) -> np.ndarray:
        """Allocatable vector: raw capacity minus the catalog's overhead for
        this type (zero overhead when the type is unknown)."""
        usable = np.array(resource_vector(node.capacity), dtype=np.float64)
        instance_type = catalog.get(node.instance_type)
        if instance_type is not None:
            usable -= resource_vector(instance_type.overhead)
        return np.maximum(usable, 0.0)

    def _used(self, pods: List[PodSpec]) -> np.ndarray:
        used = np.zeros_like(resource_vector({}), dtype=np.float64)
        for pod in pods:
            if pod.is_terminal():
                continue
            used = used + self._pod_vector(pod)
        return used

    def _utilization(self, node: NodeSpec, pods, catalog) -> float:
        usable = self._usable_capacity(node, catalog)
        used = self._used(pods)
        tracked = usable > 0
        if not tracked.any():
            return 1.0
        return float((used[tracked] / usable[tracked]).max())

    # --- batched counterfactual evaluation -----------------------------------

    def _receivers(self, catalog) -> Tuple[List[NodeSpec], np.ndarray]:
        """Live nodes eligible to absorb displaced pods, with their free
        usable headroom — tightest first (best-fit-decreasing bin order)."""
        receivers: List[Tuple[NodeSpec, np.ndarray]] = []
        for node in self.cluster.list_nodes():
            if not self._can_receive(node):
                continue
            used = self._used_on(node.name)
            if used is None:
                used = self._used(self._pods_on(node.name))
            headroom = self._usable_capacity(node, catalog) - used
            receivers.append((node, np.maximum(headroom, 0.0)))
        cpu = 0  # RESOURCE_DIMS[0] is cpu; deterministic tie-break on name
        receivers.sort(key=lambda item: (item[1][cpu], item[0].name))
        if not receivers:
            return [], np.zeros((0, resource_vector({}).shape[0]), np.float64)
        return (
            [node for node, _ in receivers],
            np.stack([headroom for _, headroom in receivers]),
        )

    @staticmethod
    def _pods_tolerate(node: NodeSpec, pods: List[PodSpec]) -> bool:
        """Every pod tolerates the receiver's NoSchedule/NoExecute taints —
        e.g. another provisioner's tainted capacity never absorbs intolerant
        pods, no matter how much headroom it has."""
        return all(
            taints_tolerate_pod(node.taints, pod.tolerations) for pod in pods
        )

    @staticmethod
    def _can_receive(node: NodeSpec) -> bool:
        return (
            node.ready
            and not node.unschedulable
            and node.deletion_timestamp is None
            and wellknown.INTERRUPTION_KIND_ANNOTATION not in node.annotations
            and wellknown.CONSOLIDATION_ACTION_ANNOTATION not in node.annotations
            and wellknown.DRIFT_ACTION_ANNOTATION not in node.annotations
            and wellknown.EMPTINESS_TIMESTAMP_ANNOTATION not in node.annotations
        )

    def _replacement_fleet(self, worker, group: List[Candidate]):
        """The replacement envelope for one provisioner's candidates: live
        instance types under the worker's EFFECTIVE constraints, usable
        capacity net of overhead and daemon overhead, cheapest allowed
        offering price per type."""
        if worker is None:
            return None
        constraints = worker.provisioner.spec.constraints
        daemons = []
        for template in self.cluster.list_daemonset_templates():
            try:
                constraints.validate_pod(template)
            except PodIncompatibleError:
                continue
            daemons.append(template)
        pods_need = np.zeros_like(resource_vector({}), dtype=np.float32)
        for candidate in group:
            if candidate.groups.num_groups:
                pods_need = np.maximum(
                    pods_need, candidate.groups.vectors.max(axis=0)
                )
        return build_fleet(
            self.cloud.get_instance_types(constraints),
            constraints,
            pods=[],
            daemons=daemons,
            pods_need=pods_need,
        )

    @staticmethod
    def _type_valid(
        group: List[Candidate], fleet: Optional[InstanceFleet]
    ) -> np.ndarray:
        """Per-candidate replacement-type mask: accelerator anti-waste — a
        type carrying accelerators the candidate's pods don't request is not
        a valid replacement (the fleet-level filter used the UNION demand so
        the axis can serve heterogeneous candidates)."""
        from karpenter_tpu.ops.encode import _ACCEL_INDEXES

        if fleet is None or fleet.num_types == 0:
            return np.zeros((len(group), 0), dtype=bool)
        demand = np.stack(
            [
                candidate.groups.vectors.T @ candidate.groups.counts
                if candidate.groups.num_groups
                else np.zeros(fleet.total.shape[1], np.float32)
                for candidate in group
            ]
        )  # [C, R]
        valid = np.ones((len(group), fleet.num_types), dtype=bool)
        for index in _ACCEL_INDEXES:
            valid &= ~(
                (fleet.total[None, :, index] > 0) & (demand[:, None, index] <= 0)
            )
        return valid

    def _evaluate(self, candidates: List[Candidate]) -> List[Action]:
        """One batched counterfactual solve per provisioner group (ONE for
        the common single-provisioner cluster); returns every cost-positive
        action, best savings first."""
        catalog = {it.name: it for it in self.cloud.get_instance_types()}
        receivers, headroom = self._receivers(catalog)
        by_provisioner: Dict[str, List[Candidate]] = {}
        for candidate in candidates:
            by_provisioner.setdefault(candidate.provisioner_name, []).append(
                candidate
            )
        actions: List[Action] = []
        for provisioner_name, group in sorted(by_provisioner.items()):
            worker = self.provisioning.worker(provisioner_name)
            fleet = self._replacement_fleet(worker, group)
            verdicts = self._solve_group(group, receivers, headroom, fleet)
            actions.extend(
                self._actions_from(group, receivers, fleet, verdicts)
            )
        actions.sort(key=lambda a: (-a.savings, a.node_name))
        return actions

    def _solve_group(
        self,
        group: List[Candidate],
        receivers: List[NodeSpec],
        headroom: np.ndarray,
        fleet: Optional[InstanceFleet],
    ) -> consolidate.ConsolidationVerdicts:
        num_dims = int(resource_vector({}).shape[0])
        num_groups = max(
            (candidate.groups.num_groups for candidate in group), default=0
        )
        num_groups = max(num_groups, 1)
        vectors = np.zeros((len(group), num_groups, num_dims), np.float32)
        counts = np.zeros((len(group), num_groups), np.int32)
        bin_mask = np.zeros((len(group), len(receivers)), bool)
        prices = np.zeros(len(group), np.float64)
        for i, candidate in enumerate(group):
            g = candidate.groups.num_groups
            if g:
                vectors[i, :g] = candidate.groups.vectors
                counts[i, :g] = candidate.groups.counts
            prices[i] = candidate.price
            if not candidate.constrained:
                # Per-candidate masking: every eligible receiver except the
                # victim itself, and only receivers whose taints the
                # candidate's pods tolerate. Constrained candidates (pods
                # with node-level scheduling requirements) keep an empty bin
                # row — their delete leg can't be verified resource-only, so
                # only the replace leg (re-solved by the provisioner, which
                # honors constraints) is scored.
                bin_mask[i] = [
                    receiver.name != candidate.node.name
                    and self._pods_tolerate(receiver, candidate.pods)
                    for receiver in receivers
                ]
        if fleet is not None and fleet.num_types:
            type_capacity, type_prices = fleet.capacity, fleet.prices
        else:
            type_capacity = np.zeros((0, num_dims), np.float32)
            type_prices = np.zeros((0,), np.float32)
        problem = consolidate.ConsolidationProblem(
            pod_vectors=vectors,
            pod_counts=counts,
            headroom=headroom.astype(np.float32),
            bin_mask=bin_mask,
            node_prices=prices,
            type_capacity=type_capacity,
            type_prices=type_prices,
            type_valid=self._type_valid(group, fleet),
        )
        return consolidate.solve_candidates(problem)

    def _actions_from(
        self, group, receivers, fleet, verdicts
    ) -> List[Action]:
        actions = []
        for i, candidate in enumerate(group):
            kind = verdicts.action[i]
            if kind == consolidate.ACTION_DELETE:
                assignment = {
                    pod.uid: receivers[j].name
                    for pod, j in consolidate.delete_assignment(
                        verdicts, i, candidate.groups.members
                    )
                }
                actions.append(
                    Action(
                        node_name=candidate.node.name,
                        kind=ACTION_DELETE,
                        savings=float(verdicts.savings[i]),
                        assignment=assignment,
                    )
                )
            elif kind == consolidate.ACTION_REPLACE:
                replacement = fleet.instance_types[int(verdicts.replace_type[i])]
                self.log.info(
                    "replace plan for %s: %s ($%.4f/hr) -> %s ($%.4f/hr)",
                    candidate.node.name, candidate.node.instance_type,
                    candidate.price, replacement.name,
                    float(verdicts.replace_price[i]),
                )
                actions.append(
                    Action(
                        node_name=candidate.node.name,
                        kind=ACTION_REPLACE,
                        savings=float(verdicts.savings[i]),
                    )
                )
        return actions

    # --- execution -----------------------------------------------------------

    def _begin(self, action: Action) -> None:
        node = self.cluster.try_get_node(action.node_name)
        if (
            node is None
            or not eligibility.voluntary_disruption_allowed(node)
            or eligibility.claim_reason(node) is not None
        ):
            return  # the cluster moved under the solve: drop the action
        # Durable intent FIRST: a controller that dies past this point
        # resumes the drain from the annotation.
        node.annotations[wellknown.CONSOLIDATION_ACTION_ANNOTATION] = action.kind
        self.cluster.update_node(node)
        self._savings[node.name] = action.savings
        # Flight-record the decision at its commit point (the annotation is
        # durable intent; this is the forensic record of WHY).
        from karpenter_tpu.utils.obs import RECORDER

        RECORDER.record(
            "consolidate",
            node=node.name,
            action=action.kind,
            instance_type=node.instance_type,
            savings=action.savings,
        )
        self.log.info(
            "consolidating %s (%s %s/%s): %s, projected savings $%.4f/hr",
            node.name, node.instance_type, node.zone, node.capacity_type,
            action.kind, action.savings,
        )
        crashpoint("consolidation.after-nominate")
        displaced = self._drain(node, action.assignment)
        # None = the drain CANCELLED the action (already counted by _cancel);
        # 0 = the whole first sweep was refused (a PDB re-check lost a race):
        # surface that once; the in-flight drain retries politely.
        if displaced == 0 and self.cluster.try_get_node(node.name) is not None:
            CONSOLIDATION_ACTIONS_TOTAL.inc(action.kind, "blocked")

    def _drain(
        self, node: NodeSpec, assignment: Optional[Dict[str, str]]
    ) -> Optional[int]:
        """One polite drain pass; returns how many pods were displaced, or
        None when the action was CANCELLED (so the caller doesn't also count
        it blocked). Completes with the finalizer-path delete once nothing
        replaceable remains."""
        pods = [
            p
            for p in self.cluster.list_pods(node_name=node.name)
            if p.survives_node_drain()
        ]
        if any(
            wellknown.DO_NOT_EVICT_ANNOTATION in p.annotations for p in pods
        ):
            # A protection appeared after nomination: consolidation is
            # voluntary, so the action is cancelled, not escalated.
            self._cancel(node)
            return None
        self.termination.terminator.cordon(node)
        displaced = self._displace_all(node, pods, assignment)
        remaining = [
            p
            for p in self.cluster.list_pods(node_name=node.name)
            if p.survives_node_drain()
        ]
        if not remaining:
            self._complete(node)
        return displaced

    def _displace_all(
        self, node: NodeSpec, pods: List[PodSpec], assignment
    ) -> int:
        displaced = 0
        for pod in pods:
            try:
                live = self.cluster.reschedule_pod(pod.namespace, pod.name)
            except PDBViolationError:
                continue  # budget spent: the drain rolls, one sweep at a time
            if live is None:
                continue  # vanished under us
            displaced += 1
            crashpoint("consolidation.mid-drain")
            target = (assignment or {}).get(pod.uid)
            if target is None or not self._rebind(live, target):
                self._feed(node, live)
        return displaced

    def _complete(self, node: NodeSpec) -> None:
        """Drained of everything replaceable: record the action, hand the
        node to the finalizer path (termination drains the daemon tail,
        deletes at the cloud) so instancegc invariants hold unchanged."""
        crashpoint("consolidation.before-delete")
        kind = node.annotations.get(
            wellknown.CONSOLIDATION_ACTION_ANNOTATION, ACTION_DELETE
        )
        savings = self._savings.pop(node.name, None)
        if savings is None and kind == ACTION_DELETE:
            # Resumed after a restart: a delete's savings IS the node price.
            catalog = {it.name: it for it in self.cloud.get_instance_types()}
            offering = self._offering(node, catalog)
            savings = offering.price if offering is not None else 0.0
        CONSOLIDATION_ACTIONS_TOTAL.inc(kind, "executed")
        CONSOLIDATION_SAVINGS_TOTAL.inc(amount=max(savings or 0.0, 0.0))
        self.cluster.delete_node(node.name)
        self.log.info("consolidated node %s drained; deleting (%s)", node.name, kind)

    def _cancel(self, node: NodeSpec) -> None:
        kind = node.annotations.get(
            wellknown.CONSOLIDATION_ACTION_ANNOTATION, ACTION_DELETE
        )
        # The dedicated removal verb: a plain update_node merge-patch cannot
        # delete the key on the apiserver backend, and a resurrected claim
        # would consume the disruption budget forever.
        self.cluster.remove_node_annotation(
            node, wellknown.CONSOLIDATION_ACTION_ANNOTATION
        )
        self._savings.pop(node.name, None)
        if (
            node.deletion_timestamp is None
            and wellknown.INTERRUPTION_KIND_ANNOTATION not in node.annotations
        ):
            node.unschedulable = False  # undo our cordon
        self.cluster.update_node(node)
        CONSOLIDATION_ACTIONS_TOTAL.inc(kind, "cancelled")
        self.log.warning(
            "consolidation of %s cancelled: a do-not-evict pod appeared "
            "mid-drain (voluntary disruption never overrides protections)",
            node.name,
        )

    def _rebind(self, pod: PodSpec, target_name: str) -> bool:
        """Bind a displaced pod onto its planned receiver if it still fits
        (fresh headroom, scheduling requirements against the live labels) —
        the kube-scheduler step this store doesn't otherwise have. False
        routes the pod through the provisioner instead."""
        target = self.cluster.try_get_node(target_name)
        if target is None or not self._can_receive(target):
            return False
        catalog = {it.name: it for it in self.cloud.get_instance_types()}
        headroom = self._usable_capacity(target, catalog) - self._used(
            self.cluster.list_pods(node_name=target.name)
        )
        if (self._pod_vector(pod) > headroom + 1e-6).any():
            return False
        if not pod.scheduling_requirements().satisfied_by_labels(target.labels):
            return False
        if not taints_tolerate_pod(target.taints, pod.tolerations):
            return False  # e.g. another provisioner's tainted capacity
        try:
            self.cluster.bind_pod(pod, target)
        except Exception:  # noqa: BLE001 — pod vanished mid-bind: nothing to place
            return False
        return True

    def _feed(self, node: NodeSpec, pod: PodSpec) -> None:
        """Replacement capacity ahead of the drain: hand the displaced pod
        straight to the owning provisioner's batch window (the interruption
        drain's pattern) so a replace-action launch is in flight while the
        rest of the victim drains."""
        name = node.labels.get(wellknown.PROVISIONER_NAME_LABEL, "")
        worker = self.provisioning.worker(name)
        if worker is not None:
            worker.add(pod)
