"""Provisioning: the per-Provisioner batching worker and its controller.

Ref: pkg/controllers/provisioning/{controller,provisioner}.go. The controller
reconciles Provisioner objects — refreshing requirements from live instance
types, hash-comparing the spec, and hot-swapping the running worker. The
worker batches incoming pods (1s idle / 10s max window, 2000-pod cap),
schedules, solves, enforces limits, launches capacity, and binds pods.
"""

from __future__ import annotations

import copy
import hashlib
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from karpenter_tpu import drift as driftlib
from karpenter_tpu.api import wellknown
from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.provisioner import PodIncompatibleError, Provisioner
from karpenter_tpu.api.requirements import Requirement, Requirements
from karpenter_tpu.api.validation import default_provisioner, validate_provisioner
from karpenter_tpu.cloudprovider import CloudProvider, NodeSpec
from karpenter_tpu.api.taints import Taint
from karpenter_tpu.controllers.cluster import Cluster, NotFoundError
from karpenter_tpu.controllers.scheduling import Scheduler
from karpenter_tpu.models.solver import GreedySolver, Solver
from karpenter_tpu.ops.ffd import PackResult
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils import tracing
from karpenter_tpu.utils.crashpoints import any_armed, crashpoint
from karpenter_tpu.utils.metrics import REGISTRY
from karpenter_tpu.utils.obs import OBS, RECORDER
from karpenter_tpu.utils.tracing import TRACER

# Batching envelope (ref: provisioner.go:42-47).
MAX_PODS_PER_BATCH = 2000
BATCH_IDLE_SECONDS = 1.0
BATCH_MAX_SECONDS = 10.0

# Admission cap per worker (batch window + overflow backlog together;
# --provision-queue-max-pods). Past it, `add` REFUSES and the pod rides
# selection's backoff requeue instead — bounded memory here, and the
# aging/retry pressure moves to the layer that already owns it. The default
# holds 25 full batch windows: a storm that deep is minutes of solve work
# away from the window anyway, so queueing more buys nothing but RSS.
DEFAULT_QUEUE_MAX_PODS = 50_000

# Pod binds fan out in parallel (ref: provisioner.go:239-247 ParallelizeUntil
# runs one goroutine per pod): each bind is an apiserver RPC in production,
# so without fan-out the bind stage dominates a large pass. The pool is
# shared across workers; goroutine-per-pod doesn't pay off for OS threads.
BIND_FANOUT = 32
_bind_pool: Optional[ThreadPoolExecutor] = None
_bind_pool_lock = threading.Lock()


def _bind_executor() -> ThreadPoolExecutor:
    global _bind_pool
    with _bind_pool_lock:
        if _bind_pool is None:
            _bind_pool = ThreadPoolExecutor(
                max_workers=BIND_FANOUT, thread_name_prefix="bind"
            )
        return _bind_pool

# Duration histograms around the three hot stages, matching the reference's
# only performance instrumentation (ref: scheduling/scheduler.go:34-47,
# binpacking/packer.go:41-55, provisioner.go:252-265 via metrics.Measure).
SCHEDULING_DURATION = REGISTRY.histogram(
    "allocation_scheduling_duration_seconds",
    "Duration of the constraint-grouping stage per batch",
)
SOLVE_DURATION = REGISTRY.histogram(
    "allocation_binpacking_duration_seconds",
    "Duration of solver packing per schedule batch (all of a pass's "
    "schedules solve together, sharing one device round trip)",
)
BIND_DURATION = REGISTRY.histogram(
    "allocation_bind_duration_seconds",
    "Duration of node creation + pod binding per packing",
)

# Overload visibility (docs/design/overload.md): current held pods per
# worker (batch window + overflow), refusals by reason, and the
# pending-cycle age of each pod at the moment its batch window closes for
# solving — the distribution a starving pod would push right.
PROVISION_QUEUE_DEPTH = REGISTRY.gauge(
    "provision_queue_depth",
    "Pods held by the provisioner worker (open batch window + overflow "
    "backlog)",
    ["provisioner"],
)
PROVISION_BACKPRESSURE_TOTAL = REGISTRY.counter(
    "provision_backpressure_total",
    "Pods refused at provisioning admission, by reason",
    ["reason"],
)
BATCH_WINDOW_AGE = REGISTRY.histogram(
    "batch_window_age_seconds",
    "Pending-cycle age of each pod when its batch window closes for "
    "solving (aging-ordered refill keeps the tail bounded under overload)",
    buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0),
)


def _batch_uids(schedules) -> List[str]:
    """Every pod uid across a pass's schedules — the lifecycle tracker's
    stamp_many unit (one lock round per phase edge for the whole batch)."""
    return [p.uid for s in schedules for p in s.pods]


def global_requirements(instance_types) -> Requirements:
    """Union of what the fleet actually offers, as In-requirements
    (ref: provisioning/controller.go:138-159 refreshes zones/types/arch/os/
    capacity-type from live instance types every reconcile)."""
    zones, names, archs, oses, capacity_types = set(), set(), set(), set(), set()
    for it in instance_types:
        zones |= set(it.zones())
        names.add(it.name)
        archs.add(it.architecture)
        oses |= set(it.operating_systems)
        capacity_types |= set(it.capacity_types())
    return Requirements(
        [
            Requirement.in_(wellknown.ZONE_LABEL, sorted(zones)),
            Requirement.in_(wellknown.INSTANCE_TYPE_LABEL, sorted(names)),
            Requirement.in_(wellknown.ARCH_LABEL, sorted(archs)),
            Requirement.in_(wellknown.OS_LABEL, sorted(oses)),
            Requirement.in_(wellknown.CAPACITY_TYPE_LABEL, sorted(capacity_types)),
        ]
    )


def spec_hash(provisioner: Provisioner) -> int:
    """Stable hash of the solver-relevant spec
    (ref: controller.go:111-125 uses hashstructure)."""
    spec = provisioner.spec
    constraints = spec.constraints
    return hash(
        (
            tuple(sorted(constraints.labels.items())),
            tuple(constraints.taints),
            constraints.requirements.canonical_key(),
            repr(sorted((constraints.provider or {}).items())),
            spec.ttl_seconds_after_empty,
            spec.ttl_seconds_until_expired,
            tuple(sorted(spec.limits.resources.items())) if spec.limits else None,
        )
    )


@dataclass
class ProvisionStats:
    scheduled_pods: int = 0
    launched_nodes: int = 0
    unschedulable_pods: int = 0
    launch_errors: List[Exception] = field(default_factory=list)


class ProvisionerWorker:
    """One batching loop per Provisioner (ref: provisioner.go:49-100 runs a
    goroutine; here `add` enqueues and `provision` drains — the runtime's
    thread loop calls provision on the batch window, tests call it directly)."""

    def __init__(
        self,
        provisioner: Provisioner,
        cluster: Cluster,
        cloud: CloudProvider,
        solver: Optional[Solver] = None,
        cluster_state=None,
        level_recorder=None,
        queue_max_pods: Optional[int] = None,
    ):
        self.provisioner = provisioner
        self.cluster = cluster
        self.cloud = cloud
        self.solver = solver or GreedySolver()
        # Admission cap (batch + overflow); see DEFAULT_QUEUE_MAX_PODS.
        self.queue_max_pods = queue_max_pods or DEFAULT_QUEUE_MAX_PODS
        # Reports each constrained solve's kernel-chosen relaxation level
        # back to selection's bookkeeping cache (selection.Preferences).
        self.level_recorder = level_recorder
        # Incremental encoder (models/cluster_state.DeviceClusterState):
        # when its delta-maintained tensors cover a schedule's batch, the
        # spec->tensor encode is skipped and the solve runs against the
        # device-resident state — O(churn) per sweep instead of O(cluster).
        self.cluster_state = cluster_state
        self.scheduler = Scheduler(cluster)
        self._pending: List[PodSpec] = []  # vet: guarded-by(self._lock)
        # Pods beyond the batch cap wait HERE, not in the selection queue: a
        # 50k-pod storm would otherwise need every overflowed pod
        # re-reconciled (1 Hz re-verify) to refill each 2000-pod batch —
        # measured at ~15s of GIL-bound queue mechanics per batch. The
        # reference survives that shape with 10k network-parked reconciles
        # (selection/controller.go:166); this runtime holds the backlog in
        # the worker and refills the window directly at each drain.
        self._overflow: List[PodSpec] = []  # vet: guarded-by(self._lock)
        self._pending_uids: set = set()  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()
        self._first_add: Optional[float] = None  # vet: guarded-by(self._lock)
        self._last_add: Optional[float] = None  # vet: guarded-by(self._lock)
        # Saturation edge state: the flight recorder gets ONE event per
        # engage/release transition, never one per refused pod (a 50k-pod
        # refusal storm would evict every launch record from the ring).
        self._saturated = False  # vet: guarded-by(self._lock)
        self._node_seq = 0

    # --- batching (ref: provisioner.go:137-163) -----------------------------

    # Set by the runtime's batch loop: workers pulse it the moment a window
    # FILLS, so a full batch provisions immediately instead of waiting out
    # the loop's poll interval (idle-closed windows still ride the poll —
    # their closing edge is a clock passing, not an event).
    batch_full: Optional[threading.Event] = None

    def add(self, pod: PodSpec) -> bool:
        """Admit a pod into the open batch window, or the overflow backlog
        once the window is full — up to the admission cap. Returns True iff
        the worker HOLDS the pod (a duplicate re-add of a held pod counts);
        False means refused at the cap, and the caller (selection) keeps the
        pod on its backoff requeue ladder, where it already ages."""
        filled = False
        refused = engaged = False
        with self._lock:
            accepted = False
            depth = len(self._pending) + len(self._overflow)
            if pod.uid not in self._pending_uids:
                if depth >= self.queue_max_pods:
                    refused = True
                    engaged = not self._saturated
                    self._saturated = True
                else:
                    accepted = True
                    depth += 1
                    if len(self._pending) >= MAX_PODS_PER_BATCH:
                        self._overflow.append(pod)
                    else:
                        self._pending.append(pod)
                        filled = len(self._pending) >= MAX_PODS_PER_BATCH
                    self._pending_uids.add(pod.uid)
                    # Window clock moves only on GENUINE adds: duplicate
                    # re-verify adds would otherwise keep refreshing _last_add
                    # and hold a partial batch open to the 10s max instead of
                    # closing on the 1s idle.
                    now = self.cluster.clock.now()
                    if self._first_add is None:
                        self._first_add = now
                    self._last_add = now
        if refused:
            PROVISION_BACKPRESSURE_TOTAL.inc("queue-full")
            if engaged:
                RECORDER.record(
                    "backpressure",
                    provisioner=self.provisioner.name,
                    phase="engage",
                    depth=self.queue_max_pods,
                )
            return False
        if accepted:
            OBS.stamp(pod.uid, "batched")
            PROVISION_QUEUE_DEPTH.set(float(depth), self.provisioner.name)
        if filled and self.batch_full is not None:
            self.batch_full.set()
        return True

    def queue_depth(self) -> int:
        """Pods currently held (open window + overflow backlog)."""
        with self._lock:
            return len(self._pending) + len(self._overflow)

    def take_backlog(self) -> List[PodSpec]:
        """Drain EVERYTHING (batch + overflow) for hand-off to a replacement
        worker on spec-hash hot-swap."""
        with self._lock:
            backlog = self._pending + self._overflow
            self._pending = []
            self._overflow = []
            self._pending_uids = set()
            self._first_add = self._last_add = None
            self._saturated = False
        PROVISION_QUEUE_DEPTH.set(0.0, self.provisioner.name)
        return backlog

    def batch_ready(self) -> bool:
        """Window closed: 1s since last add or 10s since first, or full."""
        with self._lock:
            if not self._pending:
                return False
            if len(self._pending) >= MAX_PODS_PER_BATCH:
                return True
            now = self.cluster.clock.now()
            return (
                now - self._last_add >= BATCH_IDLE_SECONDS
                or now - self._first_add >= BATCH_MAX_SECONDS
            )

    def _drain(self) -> List[PodSpec]:
        now = self.cluster.clock.now()
        released = False
        with self._lock:
            batch = self._pending
            # Refill the next window straight from the overflow backlog —
            # its pods already waited a full window, so the next batch
            # starts its clock now rather than waiting for re-verifies.
            # Under pressure the refill is AGING-ORDERED: oldest pending
            # cycle first (lifecycle-tracker anchors — re-adds after a
            # refused/rescheduled round arrive out of arrival order, and a
            # plain FIFO would let them starve behind fresher waves). The
            # OBS lock is a leaf: nothing in the tracker calls back here.
            overflow = self._overflow
            if overflow:
                anchors = OBS.pending_anchors([p.uid for p in overflow])
                order = sorted(
                    range(len(overflow)),
                    key=lambda i: (anchors.get(overflow[i].uid, now), i),
                )
                overflow = [overflow[i] for i in order]
            self._pending = overflow[:MAX_PODS_PER_BATCH]
            self._overflow = overflow[MAX_PODS_PER_BATCH:]
            self._pending_uids = {p.uid for p in self._pending} | {
                p.uid for p in self._overflow
            }
            depth = len(self._pending) + len(self._overflow)
            if self._saturated and depth < self.queue_max_pods:
                self._saturated = False
                released = True
            if self._pending:
                self._first_add = self._last_add = now
            else:
                self._first_add = self._last_add = None
        PROVISION_QUEUE_DEPTH.set(float(depth), self.provisioner.name)
        if released:
            RECORDER.record(
                "backpressure",
                provisioner=self.provisioner.name,
                phase="release",
                depth=depth,
            )
        if batch:
            anchors = OBS.pending_anchors([p.uid for p in batch])
            BATCH_WINDOW_AGE.observe_many(
                [max(0.0, now - anchors.get(p.uid, now)) for p in batch]
            )
        return batch

    # --- the provisioning pass (ref: provisioner.go:102-135) ----------------

    def _live_batch(self, batch: List[PodSpec]) -> List[PodSpec]:
        """Re-fetch to drop pods bound/terminated since batching, but keep
        scheduling the BATCH copy ("Do not mutate the pod in case the
        scheduler relaxed constraints", ref: provisioner.go:169-185)."""
        pods = []
        for pod in batch:
            live = self.cluster.try_get_pod(pod.namespace, pod.name)
            if live is None or not live.is_provisionable():
                continue
            pods.append(pod)
        return pods

    def provision(self) -> ProvisionStats:
        # One trace id per provisioning batch: every span this pass records
        # — host stages, the sidecar RPC (ridden as gRPC metadata), the SPMD
        # broadcast leg — carries it, so a merged Chrome trace stitches the
        # whole batch across processes (docs/design/observability.md).
        with TRACER.trace(tracing.new_trace_id()):
            return self._provision()

    def _provision(self) -> ProvisionStats:
        stats = ProvisionStats()
        pods = self._live_batch(self._drain())
        if not pods:
            return stats

        daemons = [
            template
            for template in self.cluster.list_daemonset_templates()
            if self._daemon_schedules_here(template)
        ]
        with SCHEDULING_DURATION.measure(), TRACER.span(
            "provision.schedule", provisioner=self.provisioner.name, pods=len(pods)
        ):
            schedules = self.scheduler.solve(self.provisioner, pods)
        OBS.stamp_many(_batch_uids(schedules), "constraint-compiled")
        # Constrained schedules (relaxation ladder, topology spread, pod
        # (anti-)affinity) route through the compiler's [L, G, T] dispatch;
        # everything else stays on the plain solver boundary. All plain
        # schedules solve as ONE batch: device-backed solvers share a
        # single device->host round trip across them, and the sidecar's
        # streaming RPC does the same across the wire (the reference loops
        # Pack per schedule — provisioner.go:102-135). On the pipelined path
        # the batch additionally OVERLAPS with bind: schedule N's nodes
        # launch and bind while schedules N+1.. are still solving on the
        # device (solve_many_pipelined).
        plain = [s for s in schedules if not s.needs_compiler]
        constrained = [s for s in schedules if s.needs_compiler]
        problems = [self._encode_problem(schedule, daemons) for schedule in plain]
        for schedule, result in self._all_results(
            plain, problems, constrained, daemons
        ):
            if stats.launch_errors and not schedule.needs_compiler:
                # An earlier schedule's launch failed (e.g. ICE): its pools
                # are now in the unavailable-offerings blackout, but this
                # schedule was solved against the pre-failure batch snapshot.
                # Re-solve it against fresh instance types so the within-pass
                # capacity feedback of the sequential loop is preserved
                # (ref: aws/instancetypes.go:174-183 blackout semantics).
                # Constrained schedules skip the re-solve: their dispatch
                # already ran after every plain launch of the pass, and a
                # late ICE heals through the next sweep's fresh compile.
                fresh_types = self.cloud.get_instance_types(schedule.constraints)
                with SOLVE_DURATION.measure(), TRACER.span(
                    "provision.resolve", pods=len(schedule.pods)
                ):
                    result = self.solver.solve(
                        schedule.pods, fresh_types, schedule.constraints, daemons
                    )
            stats.unschedulable_pods += len(result.unschedulable)
            with BIND_DURATION.measure(), TRACER.span(
                "provision.bind", nodes=result.node_count
            ):
                self._launch(schedule.constraints, result, stats)
        if stats.launched_nodes:
            live = self.cluster.try_get_provisioner(self.provisioner.name)
            if live is not None:
                live.status.last_scale_time = self.cluster.clock.now()
                self.cluster.update_provisioner_status(live)
        return stats

    def _encode_problem(self, schedule, daemons):
        """One schedule as a solver problem. Fast path: when the incremental
        encoder's pending tensors cover exactly this schedule's pods, hand
        the solver the PRE-ENCODED (groups, fleet) pair — group_pods /
        build_fleet are skipped and the kernel consumes the device-resident
        arrays (Solver._encode_problems passes the pair through). Any
        mismatch (multi-schedule pass, mid-pass churn, torn state) falls
        back to the snapshot encode, which stays authoritative."""
        instance_types = self.cloud.get_instance_types(schedule.constraints)
        if self.cluster_state is not None:
            encoded = self.cluster_state.encode_schedule(
                schedule.pods, instance_types, schedule.constraints, daemons
            )
            if encoded is not None:
                return encoded
        return (schedule.pods, instance_types, schedule.constraints, daemons)

    @staticmethod
    def _problem_pods(problem) -> int:
        # A pre-encoded problem is a (PodGroups, InstanceFleet) pair.
        return problem[0].num_pods if len(problem) == 2 else len(problem[0])

    def _all_results(self, plain, problems, constrained, daemons):
        """(schedule, result) pairs for the whole pass: the plain batch via
        the pipelined solver boundary, then each constrained schedule via
        the compiler's [L, G, T] dispatch (constraints/solve) — one kernel
        call per schedule solving every relaxation level, replacing the
        legacy relax-retry loop AND the Topology.inject pre-pass."""
        yield from self._solve_results(plain, problems)
        if not constrained:
            return
        from karpenter_tpu.constraints.solve import solve_constrained

        epoch = None
        if self.cluster_state is not None:
            try:
                # (epoch, generation): generation moves on every delta
                # flush, so the envelope cache invalidates on ordinary
                # pod/node churn, not just full re-uploads; None while
                # deltas are pending (compile reads the live store).
                # stamp_epoch folds in the market generation — a reprice
                # (live price drift past --reprice-threshold, ICE churn)
                # invalidates the compiled envelopes the same way cluster
                # churn does, so constrained solves never pack against a
                # stale price surface (docs/design/market.md).
                from karpenter_tpu.market.pricebook import stamp_epoch

                epoch = stamp_epoch(self.cluster_state.compile_tag())
            except Exception:  # noqa: BLE001 — cache tag only, never fatal
                epoch = None
        for schedule in constrained:
            instance_types = self.cloud.get_instance_types(schedule.constraints)
            schedule_uids = [p.uid for p in schedule.pods]
            OBS.stamp_many(schedule_uids, "solve-dispatched")
            with SOLVE_DURATION.measure(), TRACER.span(
                "provision.solve.constrained",
                pods=len(schedule.pods),
                levels=schedule.ladder.num_levels if schedule.ladder else 1,
            ):
                result, decision = solve_constrained(
                    self.solver, schedule, instance_types, daemons,
                    cluster=self.cluster, epoch=epoch,
                )
            OBS.stamp_many(schedule_uids, "solve-fetched")
            RECORDER.record(
                "relaxation",
                provisioner=self.provisioner.name,
                pods=len(schedule.pods),
                level=max(decision.pod_levels.values(), default=0),
                description=decision.description,
                trace=TRACER.current_trace() or "",
            )
            if self.level_recorder is not None:
                for uid, level in decision.pod_levels.items():
                    self.level_recorder(uid, level, decision.description)
            yield schedule, result

    def _solve_results(self, schedules, problems):
        """Yield (schedule, result) pairs for the pass.

        Default: the double-buffered solve->bind pipeline — the solver
        dispatches every schedule's kernel (and queues its device->host
        copy) up front, then results stream back in order, so each
        schedule's bind/launch runs while the NEXT schedules are still
        solving. When any crash test is armed the pass drops to the serial
        solve-everything-then-bind flow: a mid-bind kill must leave the
        deterministic minimal surviving state, which the battletest matrix
        asserts, and interleaving binds with in-flight solves would leave
        whatever the pipeline happened to finish (same rule as the serial
        bind path in _register_and_bind)."""
        batch_uids = _batch_uids(schedules)
        if any_armed():
            OBS.stamp_many(batch_uids, "solve-dispatched")
            with SOLVE_DURATION.measure(), TRACER.span(
                "provision.solve",
                schedules=len(problems),
                pods=sum(self._problem_pods(p) for p in problems),
            ):
                results = self.solver.solve_many(problems)
            OBS.stamp_many(batch_uids, "solve-fetched")
            yield from zip(schedules, results)
            return
        # Encode + dispatch is measured as its own sample: for device
        # solvers this covers the spec->tensor encode and the async kernel
        # dispatches of the WHOLE batch (plus any host-gated schedules'
        # synchronous solves); per-schedule pulls below then record each
        # schedule's residual solve wait — time the solver still needed
        # AFTER the previous schedule's bind, i.e. the unoverlapped
        # remainder the pipeline leaves on the critical path. Host solvers
        # solve lazily per pull (base solve_encoded_pipelined), so their
        # solve time lands in the per-schedule samples.
        with SOLVE_DURATION.measure(), TRACER.span(
            "provision.solve.dispatch",
            schedules=len(problems),
            pods=sum(self._problem_pods(p) for p in problems),
        ):
            iterator = self.solver.solve_many_pipelined(problems)
        OBS.stamp_many(batch_uids, "solve-dispatched")
        for index, schedule in enumerate(schedules):
            with SOLVE_DURATION.measure(), TRACER.span(
                "provision.solve",
                schedules=len(problems),
                schedule=index,
                pods=len(schedule.pods),
            ):
                result = next(iterator)
            OBS.stamp_many([p.uid for p in schedule.pods], "solve-fetched")
            yield schedule, result

    def _daemon_schedules_here(self, template: PodSpec) -> bool:
        try:
            self.provisioner.spec.constraints.validate_pod(template)
            return True
        except Exception:
            return False

    @staticmethod
    def _launch_identity(
        provisioner_name: str, packing, lease_generation=None
    ) -> str:
        """Stable identity of one logical launch, derived from the batch
        CONTENT: (provisioner, node count, the sorted uids of every pod the
        packing serves, and WHAT is being bought — the instance-type options
        and any pinned pool rows). A controller that crashes after the fleet
        call and re-solves the same still-unbound pods reproduces the same
        packing and therefore the same identity — the cloud provider turns
        that into a deterministic idempotency token (EC2 ClientToken) and
        adopts the instances the first attempt bought instead of buying
        twice. Pods that DID get bound before the crash drop out of the
        re-batch, changing the identity, so partially-applied launches never
        alias fresh ones. Including the purchase content guards the other
        aliasing direction: a re-solve that picks DIFFERENT pools (blackout
        caches are empty after a restart, catalogs drift) mints a fresh
        token and buys fresh capacity rather than replaying a token against
        mismatched parameters (EC2 would reject the call with
        IdempotentParameterMismatch); the first attempt's orphan is the
        leaked-capacity GC's job.

        Each uid carries its reschedule epoch (bumped when the interruption
        drain displaces the pod back to pending): a replacement launch for
        displaced pods must NOT alias the purchase that backed their dying
        node — with a bare uid it would, and the provider's idempotent
        replay would adopt the reclaimed instance and rebind the pods onto
        the very node being drained.

        `lease_generation` (the write fence's leaseTransitions value, None
        when leader election is off) folds leadership into the token: a
        stale leader re-solving the same pods under its OLD generation can
        neither alias nor adopt the successor's purchase — its orphan is
        the leaked-capacity GC's job, like any other cross-identity
        orphan."""
        from karpenter_tpu.controllers.cluster import reschedule_epoch

        pod_uids = sorted(
            f"{pod.uid or f'{pod.namespace}/{pod.name}'}@{reschedule_epoch(pod)}"
            for pod in packing.pods
        )
        type_names = sorted(t.name for t in packing.instance_type_options)
        pools = [
            f"{pool.instance_type.name}/{pool.zone}/{pool.priority}"
            for pool in (packing.pool_options or [])
        ]
        payload = "|".join(
            [provisioner_name, str(packing.node_quantity)]
            + pod_uids
            + ["types"]
            + type_names
            + ["pools"]
            + pools
            + (
                ["lease-gen", str(lease_generation)]
                if lease_generation is not None
                else []
            )
        )
        return hashlib.sha256(payload.encode()).hexdigest()[:32]

    def _launch(self, constraints, result: PackResult, stats: ProvisionStats):
        crashpoint("provision.before-launch")
        for packing in result.packings:
            # Re-GET the provisioner before every launch: abort if it was
            # deleted mid-pass, and enforce limits against fresh status
            # (ref: provisioner.go:187-195).
            live = self.cluster.try_get_provisioner(self.provisioner.name)
            if live is None or live.deletion_timestamp is not None:
                stats.unschedulable_pods += len(packing.pods)
                continue
            if live.spec.limits is not None:
                reason = live.spec.limits.exceeded_by(live.status.resources)
                if reason is not None:
                    stats.unschedulable_pods += len(packing.pods)
                    continue
            node_pods = iter(packing.pods_per_node)

            def bind_callback(
                node: NodeSpec, _pods_iter=node_pods, _packing=packing
            ):
                pods = next(_pods_iter, [])
                self._register_and_bind(
                    node, pods, extra_labels=_packing.node_labels
                )
                stats.launched_nodes += 1
                stats.scheduled_pods += len(pods)

            # Fence the purchase itself: the cloud provider is outside the
            # store, so the deposed-leader check runs here, at the caller —
            # and the launch identity carries the generation so even a check
            # that races the revocation can't alias the successor's token.
            self.cluster.fence.check("cloud.create")
            launch_id = self._launch_identity(
                self.provisioner.name, packing, self.cluster.fence.generation
            )
            # The flight-recorder's launch decision: WHAT is being bought
            # (first-choice type + price), for whom, under which idempotency
            # token — the record a breach/crash dump correlates against.
            # market_generation names the price state the purchase was made
            # under: a breach dump's launches line up against its reprice
            # events by generation (None = no live market attached).
            from karpenter_tpu.market.pricebook import active_generation

            first_pool = (packing.pool_options or [None])[0]
            RECORDER.record(
                "launch",
                provisioner=self.provisioner.name,
                nodes=packing.node_quantity,
                pods=len(packing.pods),
                instance_type=(
                    packing.instance_type_options[0].name
                    if packing.instance_type_options
                    else ""
                ),
                price=getattr(first_pool, "price", None),
                zone=getattr(first_pool, "zone", None),
                launch_id=launch_id,
                market_generation=active_generation(),
                trace=TRACER.current_trace() or "",
            )
            errors = self.cloud.create(
                constraints,
                packing.instance_type_options,
                packing.node_quantity,
                bind_callback,
                pool_options=packing.pool_options,
                launch_id=launch_id,
            )
            for error in errors:
                RECORDER.record(
                    "launch-error",
                    provisioner=self.provisioner.name,
                    launch_id=launch_id,
                    error=repr(error),
                )
            stats.launch_errors.extend(errors)

    @staticmethod
    def _pod_vanished(error: BaseException) -> bool:
        """Both backends' is-not-found: the in-memory store raises
        NotFoundError; the apiserver write-through raises ApiError 404."""
        if isinstance(error, NotFoundError):
            return True
        return getattr(error, "status", None) == 404

    def _drift_hash(self) -> str:
        """Drift identity for freshly-registered nodes: hash the STORED spec,
        never this worker's fleet-merged EFFECTIVE copy — effective
        requirements shift with the live catalog (ICE blackouts, new zones),
        and stamping them would make every market wobble look like
        provisioner drift."""
        stored = self.cluster.try_get_provisioner(self.provisioner.name)
        return driftlib.spec_hash(stored if stored is not None else self.provisioner)

    def _register_and_bind(
        self, node: NodeSpec, pods: Sequence[PodSpec], extra_labels=None
    ):
        """Create the node object (not-ready taint + termination finalizer +
        constraint labels) then bind its pods (ref: provisioner.go:209-250).
        `extra_labels` carries the packing's topology-domain labels: a
        custom-key spread domain is stamped at registration, so fresh nodes
        are born into the domain the constrained solve assigned them."""
        node.labels.setdefault(wellknown.PROVISIONER_NAME_LABEL, self.provisioner.name)
        for key, value in (extra_labels or {}).items():
            node.labels.setdefault(key, value)
        for key, value in self.provisioner.spec.constraints.labels.items():
            node.labels.setdefault(key, value)
        node.annotations.setdefault(
            wellknown.PROVISIONER_HASH_ANNOTATION, self._drift_hash()
        )
        node.taints = list(self.provisioner.spec.constraints.taints) + [
            Taint(key=wellknown.NOT_READY_TAINT_KEY, effect="NoSchedule")
        ]
        if wellknown.TERMINATION_FINALIZER not in node.finalizers:
            node.finalizers.append(wellknown.TERMINATION_FINALIZER)
        OBS.stamp_many([p.uid for p in pods], "launched")
        crashpoint("provision.before-register")
        try:
            self.cluster.create_node(node)
        except Exception as error:  # noqa: BLE001 — coded errors only
            if getattr(error, "status", None) != 409:
                raise
            # AlreadyExists: a restarted controller re-registering a node a
            # pre-crash pass already created (the cloud provider adopted the
            # instance and replayed the same NodeSpec). The object is the
            # durable record — proceed to bind against it.
            klog.named("provisioning").info(
                "node %s already registered; adopting", node.name
            )
        # Bind every pod concurrently; a failed bind is logged, not fatal
        # (ref: provisioner.go:239-247 counts successes and moves on — the
        # unbound pod stays unschedulable and retries through selection).
        def bind(pod: PodSpec) -> None:
            crashpoint("provision.mid-bind")
            try:
                self.cluster.bind_pod(pod, node)
            except Exception as error:  # noqa: BLE001
                if self._pod_vanished(error):
                    # The pod was deleted between batch collection and this
                    # bind RPC — expected under churn, nothing to retry
                    # (controller-runtime's IgnoreNotFound contract).
                    klog.named("provisioning").debug(
                        "pod %s/%s vanished before bind to %s",
                        pod.namespace, pod.name, node.name,
                    )
                    return
                klog.named("provisioning").exception(
                    "failed to bind %s/%s to %s", pod.namespace, pod.name, node.name
                )

        # Serial path for singleton binds AND whenever a crash test is armed:
        # a mid-bind kill must leave the deterministic minimal surviving
        # state (pods before the crash index bound, none after), not
        # whatever sibling binds the executor happened to finish first.
        if len(pods) <= 1 or any_armed():
            for pod in pods:
                bind(pod)
            crashpoint("provision.after-bind")
            return
        futures = []
        for index, pod in enumerate(pods):
            try:
                futures.append(_bind_executor().submit(bind, pod))
            except RuntimeError:
                # Interpreter teardown: atexit shut the shared pool down
                # while a daemon batch thread was mid-provision. Only the
                # NOT-YET-SUBMITTED pods need the inline fallback — the
                # already-submitted ones ran (or will run) on the pool, and
                # re-binding them would double-bind.
                for late in pods[index:]:
                    bind(late)
                break
        for future in futures:
            future.result()
        crashpoint("provision.after-bind")


class ProvisioningController:
    """Reconciles Provisioner objects and manages workers
    (ref: provisioning/controller.go:64-125). Requeues every 5 minutes in the
    runtime to pick up instance-type drift."""

    REQUEUE_SECONDS = 300.0

    def __init__(
        self,
        cluster: Cluster,
        cloud: CloudProvider,
        solver: Optional[Solver] = None,
        cluster_state=None,
        queue_max_pods: Optional[int] = None,
    ):
        self.cluster = cluster
        self.cloud = cloud
        self.solver = solver
        self.cluster_state = cluster_state
        self.queue_max_pods = queue_max_pods
        self.workers: Dict[str, ProvisionerWorker] = {}
        self._hashes: Dict[str, int] = {}
        # Runtime wiring (runtime.Manager): propagated to every worker so a
        # filling batch window wakes the batch loop immediately.
        self.batch_full: Optional[threading.Event] = None
        # Set by SelectionController: receives (uid, level, description) for
        # every constrained solve. Late-bound — workers route through
        # _record_level so construction order doesn't matter.
        self.level_recorder = None

    def _record_level(self, uid: str, level: int, description: str = "") -> None:
        if self.level_recorder is not None:
            self.level_recorder(uid, level, description)

    def reconcile(self, name: str) -> None:
        provisioner = self.cluster.try_get_provisioner(name)
        if provisioner is None or provisioner.deletion_timestamp is not None:
            if self.workers.pop(name, None) is not None:
                # The worker's depth series would otherwise freeze at its
                # last value forever on the deleted provisioner's label.
                PROVISION_QUEUE_DEPTH.set(0.0, name)
            self._hashes.pop(name, None)
            return
        self.apply(provisioner)

    def apply(self, provisioner: Provisioner) -> None:
        default_provisioner(provisioner)
        validate_provisioner(provisioner)
        # Constrain a WORKING COPY to what the fleet offers
        # (ref: controller.go:91-108). The stored spec stays pristine: each
        # reconcile re-derives the intersection from it, so offerings that
        # come back after an ICE blackout (or newly added types/zones) widen
        # the envelope again instead of being ratcheted away.
        instance_types = self.cloud.get_instance_types()
        requirements = (
            provisioner.spec.constraints.requirements.merge(
                global_requirements(instance_types)
            )
            .merge(Requirements.from_labels(provisioner.spec.constraints.labels))
            .consolidate()
        )
        effective = copy.deepcopy(provisioner)
        effective.spec.constraints.requirements = requirements
        new_hash = spec_hash(effective)
        if self._hashes.get(provisioner.name) != new_hash:
            self._hashes[provisioner.name] = new_hash
            replacement = ProvisionerWorker(
                effective, self.cluster, self.cloud, self.solver,
                cluster_state=self.cluster_state,
                level_recorder=self._record_level,
                queue_max_pods=self.queue_max_pods,
            )
            replacement.batch_full = self.batch_full
            # Hand the old worker's accepted backlog (batch + overflow) to
            # the replacement: mid-storm spec-hash flips (ICE blackouts
            # changing effective offerings) must not dump tens of thousands
            # of parked pods back onto the slow selection re-verify path.
            # Re-validate against the CHANGED constraints at hand-off — the
            # hash flipped precisely because they changed; pods now
            # incompatible stay out and heal through the selection
            # re-verify, which relaxes and can re-route them.
            old = self.workers.get(provisioner.name)
            if old is not None:
                for pod in old.take_backlog():
                    try:
                        effective.spec.constraints.validate_pod(pod)
                    except PodIncompatibleError:
                        continue
                    replacement.add(pod)
            self.workers[provisioner.name] = replacement
        else:
            self.workers[provisioner.name].provisioner = effective
        # A provisioner with a running worker is ready to scale — the Active
        # status condition (ref: provisioner_status.go:40-50 knative
        # conditions; the v0.5.x reference defines but barely drives it).
        if provisioner.status.conditions.get("Active") is not True:
            provisioner.status.conditions["Active"] = True
            self.cluster.update_provisioner_status(provisioner)

    def worker(self, name: str) -> Optional[ProvisionerWorker]:
        return self.workers.get(name)
