"""Node lifecycle: readiness, liveness, expiration, emptiness, finalizer.

Ref: pkg/controllers/node/*.go — an umbrella reconciler runs five
sub-reconcilers over every karpenter-managed node and requeues at the soonest
of their requested times (ref: utils/result/result.go Min combinator).
"""

from __future__ import annotations

from typing import List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.taints import Taint
from karpenter_tpu.cloudprovider import NodeSpec
from karpenter_tpu.controllers import eligibility
from karpenter_tpu.controllers.cluster import Cluster

LIVENESS_TIMEOUT_SECONDS = 15 * 60  # ref: node/liveness.go:31


def _min_requeue(*results: Optional[float]) -> Optional[float]:
    values = [r for r in results if r is not None]
    return min(values) if values else None


class Readiness:
    """Strip the not-ready taint once the kubelet reports Ready, and — the
    other direction the reference never implemented — re-add it when a node
    that HAD joined goes NotReady, so the solver stops packing onto a sick
    node (ref: node/readiness.go:27-41; the one-way-taint gap)."""

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        if not node.ready:
            # Only nodes that once reported get the taint re-added: a
            # never-joined node still carries its registration taint, and
            # re-tainting it here would double-write every liveness wait.
            if node.status_reported_at is not None and not any(
                t.key == wellknown.NOT_READY_TAINT_KEY for t in node.taints
            ):
                node.taints.append(
                    Taint(key=wellknown.NOT_READY_TAINT_KEY, effect="NoSchedule")
                )
                cluster.update_node(node)
            return None
        before = len(node.taints)
        node.taints = [
            t for t in node.taints if t.key != wellknown.NOT_READY_TAINT_KEY
        ]
        if len(node.taints) != before:
            cluster.update_node(node)
            # The node-ready lifecycle edge: pods already bound here waited
            # on the kubelet — attribute that wait to their node-ready phase.
            from karpenter_tpu.utils.obs import OBS

            OBS.stamp_many(
                [p.uid for p in cluster.list_pods(node_name=node.name)],
                "node-ready",
            )
        return None

    # taint list uses Taint dataclass; imported for type parity
    _ = Taint


class Liveness:
    """Delete nodes whose kubelet never reported within the timeout — the
    runaway-scale guard (ref: node/liveness.go:31-52, designs/limits.md).

    Deliberately scoped to the NEVER-joined case: a node that reported once
    and then went dark is the health controller's job
    (controllers/health.py), which drains and replaces instead of deleting
    out from under still-running pods."""

    def __init__(self, timeout: float = LIVENESS_TIMEOUT_SECONDS):
        self.timeout = timeout

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        if node.status_reported_at is not None:
            return None
        age = cluster.clock.now() - node.created_at
        if age >= self.timeout:
            cluster.delete_node(node.name)
            return None
        return self.timeout - age


class Expiration:
    """Delete nodes older than ttlSecondsUntilExpired — the node-upgrade /
    chaos mechanism (ref: node/expiration.go:37-52)."""

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        ttl = provisioner.spec.ttl_seconds_until_expired
        if ttl is None:
            return None
        age = cluster.clock.now() - node.created_at
        if age >= ttl:
            cluster.delete_node(node.name)
            return None
        return ttl - age


class Emptiness:
    """Stamp/clear the emptiness timestamp; delete past ttlSecondsAfterEmpty
    (ref: node/emptiness.go:38-99)."""

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        ttl = provisioner.spec.ttl_seconds_after_empty
        if ttl is None:
            return None
        # Shared voluntary-disruption gate (controllers/eligibility.py): the
        # same predicate consolidation nominates through, so an interrupted
        # or already-deleting node can't be claimed by both paths at once.
        if not eligibility.voluntary_disruption_allowed(node):
            return None
        if not eligibility.is_empty(cluster, node):
            if wellknown.EMPTINESS_TIMESTAMP_ANNOTATION in node.annotations:
                # The dedicated removal verb: a plain update_node merge-patch
                # cannot delete the key on the apiserver backend.
                cluster.remove_node_annotation(
                    node, wellknown.EMPTINESS_TIMESTAMP_ANNOTATION
                )
            return None
        stamp = node.annotations.get(wellknown.EMPTINESS_TIMESTAMP_ANNOTATION)
        now = cluster.clock.now()
        if stamp is None:
            node.annotations[wellknown.EMPTINESS_TIMESTAMP_ANNOTATION] = str(now)
            cluster.update_node(node)
            return ttl
        elapsed = now - float(stamp)
        if elapsed >= ttl:
            cluster.delete_node(node.name)
            return None
        return ttl - elapsed


class Finalizer:
    """Re-add the termination finalizer to nodes that lost or never had it
    (ref: node/finalizer.go:28-40)."""

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        if node.deletion_timestamp is not None:
            return None
        if wellknown.TERMINATION_FINALIZER not in node.finalizers:
            node.finalizers.append(wellknown.TERMINATION_FINALIZER)
            cluster.update_node(node)
        return None


class NodeController:
    """Umbrella reconciler (ref: node/controller.go:61-115): only
    karpenter-labeled nodes, skip deleting ones, run sub-reconcilers, requeue
    at the soonest requested time."""

    def __init__(self, cluster: Cluster, liveness_timeout: float = LIVENESS_TIMEOUT_SECONDS):
        self.cluster = cluster
        self.reconcilers = [
            Readiness(),
            Liveness(timeout=liveness_timeout),
            Expiration(),
            Emptiness(),
            Finalizer(),
        ]

    def reconcile(self, name: str) -> Optional[float]:
        node = self.cluster.try_get_node(name)
        if node is None or node.deletion_timestamp is not None:
            return None
        provisioner_name = node.labels.get(wellknown.PROVISIONER_NAME_LABEL)
        if provisioner_name is None:
            return None  # not ours
        provisioner = self.cluster.try_get_provisioner(provisioner_name)
        if provisioner is None:
            return None
        results = []
        for reconciler in self.reconcilers:
            results.append(reconciler.reconcile(self.cluster, provisioner, node))
            # RE-READ between sub-reconcilers, don't just probe existence:
            # on the apiserver backend a watch event (kubelet heartbeat, a
            # rival controller's patch) can REPLACE the cached object
            # mid-sequence, and the next sub-reconciler writing through the
            # stale reference would undo that update. The refreshed object
            # also catches a sub-reconciler's own delete (deletion held by
            # the finalizer), which ends the pass.
            node = self.cluster.try_get_node(name)
            if node is None or node.deletion_timestamp is not None:
                return None  # a sub-reconciler (or a rival) deleted the node
        return _min_requeue(*results)
