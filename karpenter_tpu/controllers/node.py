"""Node lifecycle: readiness, liveness, expiration, emptiness, finalizer.

Ref: pkg/controllers/node/*.go — an umbrella reconciler runs five
sub-reconcilers over every karpenter-managed node and requeues at the soonest
of their requested times (ref: utils/result/result.go Min combinator).
"""

from __future__ import annotations

from typing import List, Optional

from karpenter_tpu import drift as driftlib
from karpenter_tpu.api import wellknown
from karpenter_tpu.api.taints import Taint
from karpenter_tpu.cloudprovider import NodeSpec
from karpenter_tpu.controllers import eligibility
from karpenter_tpu.controllers.cluster import Cluster

LIVENESS_TIMEOUT_SECONDS = 15 * 60  # ref: node/liveness.go:31
# How soon a budget-starved expiration/emptiness retries its claim.
BUDGET_REQUEUE_SECONDS = 10.0


def _min_requeue(*results: Optional[float]) -> Optional[float]:
    values = [r for r in results if r is not None]
    return min(values) if values else None


class Readiness:
    """Strip the not-ready taint once the kubelet reports Ready, and — the
    other direction the reference never implemented — re-add it when a node
    that HAD joined goes NotReady, so the solver stops packing onto a sick
    node (ref: node/readiness.go:27-41; the one-way-taint gap)."""

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        if not node.ready:
            # Only nodes that once reported get the taint re-added: a
            # never-joined node still carries its registration taint, and
            # re-tainting it here would double-write every liveness wait.
            if node.status_reported_at is not None and not any(
                t.key == wellknown.NOT_READY_TAINT_KEY for t in node.taints
            ):
                node.taints.append(
                    Taint(key=wellknown.NOT_READY_TAINT_KEY, effect="NoSchedule")
                )
                cluster.update_node(node)
            return None
        before = len(node.taints)
        node.taints = [
            t for t in node.taints if t.key != wellknown.NOT_READY_TAINT_KEY
        ]
        if len(node.taints) != before:
            cluster.update_node(node)
            # The node-ready lifecycle edge: pods already bound here waited
            # on the kubelet — attribute that wait to their node-ready phase.
            from karpenter_tpu.utils.obs import OBS

            OBS.stamp_many(
                [p.uid for p in cluster.list_pods(node_name=node.name)],
                "node-ready",
            )
        return None

    # taint list uses Taint dataclass; imported for type parity
    _ = Taint


class Liveness:
    """Delete nodes whose kubelet never reported within the timeout — the
    runaway-scale guard (ref: node/liveness.go:31-52, designs/limits.md).

    Deliberately scoped to the NEVER-joined case: a node that reported once
    and then went dark is the health controller's job
    (controllers/health.py), which drains and replaces instead of deleting
    out from under still-running pods."""

    def __init__(self, timeout: float = LIVENESS_TIMEOUT_SECONDS):
        self.timeout = timeout

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        if node.status_reported_at is not None:
            return None
        age = cluster.clock.now() - node.created_at
        if age >= self.timeout:
            cluster.delete_node(node.name)
            return None
        return self.timeout - age


class HashStamp:
    """Back-fill the provisioner-hash annotation on nodes that predate drift
    detection (legacy/adopted capacity). A missing hash is NEVER drift: the
    node is stamped with the CURRENT stored-spec hash and participates in
    spec-hash drift from the next spec change onward — adopting a fleet must
    not instantly nominate all of it for replacement."""

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        if wellknown.PROVISIONER_HASH_ANNOTATION not in node.annotations:
            node.annotations[wellknown.PROVISIONER_HASH_ANNOTATION] = (
                driftlib.spec_hash(provisioner)
            )
            cluster.update_node(node)
        return None


class Expiration:
    """Expire nodes older than ttlSecondsUntilExpired — the node-upgrade /
    chaos mechanism (ref: node/expiration.go:37-52), rewired through the
    drift machinery: an expired node is just drift of kind "expired". The
    claim is the durable drift-action annotation, budgeted through the
    shared DisruptionLedger, so N simultaneously-expired nodes roll
    budget-at-a-time instead of the whole fleet deleting at once. Deletion
    still happens right here (the finalizer drain takes over), so expiration
    works even where the drift controller isn't running; when it IS running,
    its sweep sees the same annotation and never double-claims."""

    def __init__(self, ledger: Optional[eligibility.DisruptionLedger] = None):
        self.ledger = ledger

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        ttl = provisioner.spec.ttl_seconds_until_expired
        if ttl is None:
            return None
        age = cluster.clock.now() - node.created_at
        if age < ttl:
            return ttl - age
        if wellknown.DRIFT_ACTION_ANNOTATION in node.annotations:
            return None  # already claimed (by us earlier, or the drift sweep)
        if wellknown.INTERRUPTION_KIND_ANNOTATION in node.annotations:
            return None  # the reclamation drain owns it; it's dying anyway
        if eligibility.claim_reason(node) is not None:
            return BUDGET_REQUEUE_SECONDS  # another voluntary actor owns it
        ledger = self.ledger or eligibility.DisruptionLedger(cluster)
        if ledger.headroom(eligibility.REASON_DRIFT) <= 0:
            return BUDGET_REQUEUE_SECONDS  # budget spent: roll on a later pass
        node.annotations[wellknown.DRIFT_ACTION_ANNOTATION] = (
            driftlib.DRIFT_KIND_EXPIRED
        )
        cluster.update_node(node)
        # Lazy import: controllers.drift pulls in provisioning/termination,
        # which this leaf module must not import at module load.
        from karpenter_tpu.controllers.drift import DRIFT_REPLACEMENTS_TOTAL
        from karpenter_tpu.utils.obs import RECORDER

        RECORDER.record(
            "drift",
            node=node.name,
            drift_kind=driftlib.DRIFT_KIND_EXPIRED,
            reason=f"node age {age:.0f}s >= ttlSecondsUntilExpired {ttl}s",
        )
        DRIFT_REPLACEMENTS_TOTAL.inc(driftlib.DRIFT_KIND_EXPIRED, "executed")
        cluster.delete_node(node.name)
        return None


class Emptiness:
    """Stamp/clear the emptiness timestamp; delete past ttlSecondsAfterEmpty
    (ref: node/emptiness.go:38-99). The delete consults the shared
    DisruptionLedger: a stamped-and-waiting empty node costs nothing, but
    actually deleting one is a voluntary disruption like any other."""

    def __init__(self, ledger: Optional[eligibility.DisruptionLedger] = None):
        self.ledger = ledger

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        ttl = provisioner.spec.ttl_seconds_after_empty
        if ttl is None:
            return None
        # Shared voluntary-disruption gate (controllers/eligibility.py): the
        # same predicate consolidation nominates through, so an interrupted
        # or already-deleting node can't be claimed by both paths at once.
        if not eligibility.voluntary_disruption_allowed(node):
            return None
        if not eligibility.is_empty(cluster, node):
            if wellknown.EMPTINESS_TIMESTAMP_ANNOTATION in node.annotations:
                # The dedicated removal verb: a plain update_node merge-patch
                # cannot delete the key on the apiserver backend.
                cluster.remove_node_annotation(
                    node, wellknown.EMPTINESS_TIMESTAMP_ANNOTATION
                )
            return None
        stamp = node.annotations.get(wellknown.EMPTINESS_TIMESTAMP_ANNOTATION)
        now = cluster.clock.now()
        if stamp is None:
            node.annotations[wellknown.EMPTINESS_TIMESTAMP_ANNOTATION] = str(now)
            cluster.update_node(node)
            return ttl
        elapsed = now - float(stamp)
        if elapsed >= ttl:
            ledger = self.ledger or eligibility.DisruptionLedger(cluster)
            if ledger.headroom(eligibility.REASON_EMPTINESS) <= 0:
                return BUDGET_REQUEUE_SECONDS  # budget spent: retry shortly
            cluster.delete_node(node.name)
            return None
        return ttl - elapsed


class Finalizer:
    """Re-add the termination finalizer to nodes that lost or never had it
    (ref: node/finalizer.go:28-40)."""

    def reconcile(self, cluster: Cluster, provisioner, node: NodeSpec) -> Optional[float]:
        if node.deletion_timestamp is not None:
            return None
        if wellknown.TERMINATION_FINALIZER not in node.finalizers:
            node.finalizers.append(wellknown.TERMINATION_FINALIZER)
            cluster.update_node(node)
        return None


class NodeController:
    """Umbrella reconciler (ref: node/controller.go:61-115): only
    karpenter-labeled nodes, skip deleting ones, run sub-reconcilers, requeue
    at the soonest requested time."""

    def __init__(
        self,
        cluster: Cluster,
        liveness_timeout: float = LIVENESS_TIMEOUT_SECONDS,
        ledger: Optional[eligibility.DisruptionLedger] = None,
    ):
        self.cluster = cluster
        self.reconcilers = [
            Readiness(),
            Liveness(timeout=liveness_timeout),
            HashStamp(),
            Expiration(ledger=ledger),
            Emptiness(ledger=ledger),
            Finalizer(),
        ]

    def reconcile(self, name: str) -> Optional[float]:
        node = self.cluster.try_get_node(name)
        if node is None or node.deletion_timestamp is not None:
            return None
        provisioner_name = node.labels.get(wellknown.PROVISIONER_NAME_LABEL)
        if provisioner_name is None:
            return None  # not ours
        provisioner = self.cluster.try_get_provisioner(provisioner_name)
        if provisioner is None:
            return None
        results = []
        for reconciler in self.reconcilers:
            results.append(reconciler.reconcile(self.cluster, provisioner, node))
            # RE-READ between sub-reconcilers, don't just probe existence:
            # on the apiserver backend a watch event (kubelet heartbeat, a
            # rival controller's patch) can REPLACE the cached object
            # mid-sequence, and the next sub-reconciler writing through the
            # stale reference would undo that update. The refreshed object
            # also catches a sub-reconciler's own delete (deletion held by
            # the finalizer), which ends the pass.
            node = self.cluster.try_get_node(name)
            if node is None or node.deletion_timestamp is not None:
                return None  # a sub-reconciler (or a rival) deleted the node
        return _min_requeue(*results)
