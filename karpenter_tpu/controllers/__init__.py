"""Control plane: the reconciler shell around the solver.

Ref layout (pkg/controllers/*): selection routes unschedulable pods to
provisioners; provisioning batches + solves + launches + binds; termination
drains and deletes; node runs lifecycle sub-reconcilers; counter aggregates
capacity; metrics publishes gauges. The kube-apiserver is replaced by the
in-memory Cluster state store (controllers/cluster.py), which tests and the
single-process runtime share.
"""
