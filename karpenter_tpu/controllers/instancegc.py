"""Leaked-capacity garbage collection.

A controller that dies between `create_fleet` returning and node
registration leaves a PAID instance with no Node object pointing at it —
nothing else in the pipeline ever revisits such an instance: it is not a
Node (no lifecycle reconcile), its pods were never bound (selection retries
them onto NEW capacity), and the provider keeps billing. The reference
ecosystem handles this with a cloud-side garbage collector reconciling
provider instances against cluster Nodes by the ownership tag; this
controller carries that reaper for every `CloudProvider` that can enumerate
owned capacity (`list_instances`).

Semantics (the podgc pattern, hardened for money):

- **Launch grace TTL**: an instance younger than `grace_seconds` is never a
  candidate — the launch→register window is seconds, but a slow bootstrap
  (AMI pull, kubelet join) must not get its capacity shot out from under it.
  When the provider can't report `launched_at` (0.0 = unknown), the grace
  clock runs from the first GC sighting instead.
- **Two consecutive sightings**: a single observation can be a transient
  ordering window (DescribeInstances returning before the Node watch event
  lands, or a Node flapping through a re-register). Termination requires
  the same orphan on two sweeps in a row.
- **Terminate-or-retry**: a failed terminate keeps the instance a suspect,
  so the very next sweep retries; success counts `instancegc_terminated_total`
  — the alert signal that the control plane is leaking launches.
"""

from __future__ import annotations

from typing import Dict

from karpenter_tpu.cloudprovider import CloudProvider
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.metrics import REGISTRY

log = klog.named("instancegc")

SWEEP_SECONDS = 30.0
# Launch→register grace: generous against slow node bootstraps, tiny against
# the forever-leak it bounds (a v4-8 slice leaked overnight costs more than
# this window ever can).
LAUNCH_GRACE_SECONDS = 300.0

INSTANCEGC_TERMINATED_TOTAL = REGISTRY.counter(
    "instancegc_terminated_total",
    "Leaked instances terminated (owned capacity never matched by a Node)",
)
INSTANCEGC_SUSPECTS = REGISTRY.gauge(
    "instancegc_suspect_count",
    "Node-less owned instances awaiting a second sighting or grace expiry",
)


class InstanceGcController:
    """Periodic sweep (Manager drives it like podgc): terminate owned
    provider instances that no cluster Node accounts for."""

    def __init__(
        self,
        cluster: Cluster,
        cloud: CloudProvider,
        grace_seconds: float = LAUNCH_GRACE_SECONDS,
    ):
        self.cluster = cluster
        self.cloud = cloud
        self.grace_seconds = grace_seconds
        # provider_id -> time of FIRST consecutive sighting; doubles as the
        # grace anchor for instances with unknown launched_at.
        self._suspects: Dict[str, float] = {}

    def reconcile(self, _key=None) -> float:
        now = self.cluster.clock.now()
        node_ids = {
            node.provider_id
            for node in self.cluster.list_nodes()
            if node.provider_id
        }
        orphans = {}
        for instance in self.cloud.list_instances():
            if instance.provider_id in node_ids:
                continue
            if (
                instance.launched_at
                and now - instance.launched_at < self.grace_seconds
            ):
                # Within the launch grace TTL: a normal launch still
                # registering. Not even a suspect yet — the sighting clock
                # starts once the instance is old enough to be suspicious.
                continue
            orphans[instance.provider_id] = instance
        next_suspects: Dict[str, float] = {}
        for provider_id, instance in orphans.items():
            first_seen = self._suspects.get(provider_id)
            if first_seen is None:
                next_suspects[provider_id] = now  # first sighting: wait one sweep
                continue
            if not instance.launched_at and now - first_seen < self.grace_seconds:
                # Unknown launch time: run the grace window from the first
                # sighting so a provider with no launchTime still gets the
                # register window before its capacity is reaped.
                next_suspects[provider_id] = first_seen
                continue
            try:
                # Fenced like every other provider mutation: a deposed
                # leader must not reap capacity the successor may have just
                # registered (utils/fence.py).
                self.cluster.fence.check("cloud.terminate")
                self.cloud.terminate_instance(instance)
            except Exception:  # noqa: BLE001 — transient provider failure:
                # STAY a suspect so the very next sweep retries.
                log.exception(
                    "failed to terminate leaked instance %s; retrying",
                    instance.instance_id,
                )
                next_suspects[provider_id] = first_seen
                continue
            INSTANCEGC_TERMINATED_TOTAL.inc()
            log.warning(
                "terminated leaked instance %s (%s in %s, launched %s, "
                "no Node after %.0fs grace)",
                instance.instance_id,
                instance.instance_type,
                instance.zone,
                instance.launched_at or "unknown",
                self.grace_seconds,
            )
        self._suspects = next_suspects
        INSTANCEGC_SUSPECTS.set(len(self._suspects))
        return SWEEP_SECONDS
