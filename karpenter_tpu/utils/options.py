"""Static configuration: flags + environment defaults.

Ref: pkg/utils/options/options.go:27-69 and pkg/utils/env/env.go — the
reference parses flags with env-var fallbacks, validates at boot, and injects
the result through context. We parse argv/env into an Options dataclass that
the runtime threads through explicitly.
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field
from typing import List, Optional


class OptionsError(Exception):
    pass


def _env(name: str, default: str) -> str:
    return os.environ.get(name, default)


@dataclass
class Options:
    cluster_name: str = ""
    cluster_endpoint: str = ""
    metrics_port: int = 8080  # ref: main.go:83 / chart deployment.yaml:37-41
    health_probe_port: int = 8081  # ref: manager.go:52-57
    kube_client_qps: float = 200.0  # ref: options.go:33
    kube_client_burst: int = 300  # ref: options.go:34
    # Kube API retry envelope (kubeapi/client.py RetryPolicy; see
    # docs/design/chaos.md for the policy table and docs/operations.md for
    # when to tune these): attempt budget per request, then the capped
    # exponential backoff between attempts. Raise the cap when riding out
    # long apiserver brownouts; lower attempts to fail fast into the
    # reconcile loops' own backoff.
    kube_retry_max_attempts: int = 5
    kube_retry_backoff_base: float = 0.1
    kube_retry_backoff_cap: float = 5.0
    # Watch read-deadline: a watch stream quiet for this long is torn and
    # reconnected (an apiserver that stops sending bytes must not hang the
    # pump forever). Keep well above the server's bookmark cadence.
    kube_watch_idle_timeout: float = 300.0
    solver: str = "cost"  # cost | ffd | greedy | native | remote
    solver_endpoint: str = ""  # remote: host:port of the solver sidecar
    cloud_provider: str = "fake"
    leader_election: bool = True
    log_level: str = "info"
    # Cluster-store backend (ref: manager.go:33-66 — the reference always
    # talks to a live apiserver; we also keep the in-memory store for tests
    # and standalone runs):
    #   memory     — in-process store (the envtest analogue)
    #   incluster  — apiserver via the mounted serviceaccount
    #   <URL>      — apiserver at an explicit base URL (kubeconfig-less dev;
    #                token from KUBE_TOKEN, CA from KUBE_CA_FILE)
    cluster_store: str = "memory"
    # Selection reconcile threads. The reference runs selection at
    # MaxConcurrentReconciles=10,000 (selection/controller.go:166) because
    # each reconcile blocks on network I/O; here reconciles read the
    # informer cache (CPU-bound under the GIL), and the pod-storm benchmark
    # (bench.py bench_pod_storm: 10k pods through the running Manager) shows
    # ~1.8s drain at 8 threads and within ~20% of that at 128 (chunked
    # dispatch + wake coalescing keep the pool flat; overflow backlog
    # lives in the worker) — so the envelope is the cheapest setting that
    # keeps up: 8.
    selection_concurrency: int = 8
    # Fraction of an interruption's reclaim window spent draining politely
    # (PDB-respecting, do-not-evict honored) before the drain overrides both
    # rather than losing pods to the reclaim (controllers/interruption.py).
    interruption_escalate_fraction: float = 0.5
    # Disruption budget for the consolidation sweep: at most this many nodes
    # voluntarily disrupted per sweep (in-flight victims count against it);
    # 0 disables consolidation entirely (controllers/consolidation.py).
    consolidation_max_disruption: int = 1
    # Seconds of quiet after any interruption/termination activity before
    # consolidation acts again — the voluntary path yields to reclamation.
    consolidation_cooldown: float = 60.0
    # Fleet-wide voluntary-disruption budget (controllers/eligibility.py
    # DisruptionLedger): at most this many voluntary disruptions —
    # consolidation + drift/expiration + emptiness deletes together — may
    # be in flight at once; 0 disables ALL voluntary disruption. Per-reason
    # caps (consolidation-max-disruption, drift-max-disruption) nest inside.
    disruption_budget: int = 10
    # Whether the drift sweep runs at all (spec-hash, provider-side, and
    # expiration detection; controllers/drift.py).
    drift_enabled: bool = True
    # Per-sweep cap on NEW drift/expiration victims (the drift reason's
    # slice of the shared budget); 0 pauses drift replacement while leaving
    # detection (drift_nodes gauge) running.
    drift_max_disruption: int = 2
    # Pod-latency SLO targets (utils/obs.py SloEvaluator): rolling-window
    # p99 ceilings for end-to-end pending time and time-to-first-launch.
    # Exceeding a target counts slo_breaches_total{slo} and triggers a
    # flight-recorder dump (KARPENTER_FLIGHT_DIR). 0 disables the objective
    # — the gauges still publish. See docs/design/observability.md.
    slo_pending_p99: float = 0.0
    slo_ttfl: float = 0.0
    # Live market dynamics (karpenter_tpu/market): relative spot-discount
    # drift (vs the pool's anchor at its last reprice) that bumps the
    # PriceBook generation — invalidating the compiled-envelope and fleet
    # caches and requeueing provisioning + consolidation. Smaller = more
    # responsive to drift, more re-solves. See docs/design/market.md and
    # the operations.md "price storm" runbook.
    reprice_threshold: float = 0.1
    # Per-pool floor between reprice-triggered requeues (seconds): bumps
    # inside the window coalesce, so a price storm costs at most one
    # re-solve per pool per window and cannot melt the sweep loops.
    reprice_debounce: float = 5.0
    # Market feed poll cadence (seconds). 0 (the default) = auto: the
    # provider's own MARKET_POLL_DEFAULT_S — 1s for the in-memory fake,
    # 15s on EC2 where each sweep is a paginated DescribeSpotPriceHistory
    # (the reference's drift requeue runs at 5 MINUTES). Set explicitly to
    # override either.
    market_poll_interval: float = 0.0
    # Tombstone-density trigger for the incremental encoder's masked
    # compaction (models/cluster_state.py): when freed-but-unreused slot
    # rows exceed this fraction of the high-water mark, live rows are
    # packed to the front and the device arrays re-uploaded (epoch bump).
    # Lower = tighter arrays, more re-uploads; 1.0 effectively disables
    # compaction. See docs/operations.md.
    encode_compaction_threshold: float = 0.5
    # Node-health ladder (controllers/health.py; docs/design/
    # node-lifecycle.md and the operations.md "unhealthy node" runbook):
    # heartbeat age past which a JOINED node counts unreachable — kube's
    # node-monitor-grace-period analogue. The escalation ladder engages
    # after STALE_OBSERVATIONS consecutive unhealthy sweeps.
    node_unreachable_timeout: float = 60.0
    # How long a node may exist without its kubelet EVER reporting before
    # the Liveness guard deletes it (controllers/node.py; replaces the old
    # LIVENESS_TIMEOUT_SECONDS constant as the wired value). Must cover the
    # instancegc launch grace: deleting a never-joined node earlier than
    # the GC's bootstrap window races a legitimately slow bootstrap.
    node_liveness_timeout: float = 900.0
    # Polite-drain budget for a confirmed-unhealthy node; past it the drain
    # escalates over PDBs and do-not-evict (counted on
    # drain_stalled_total{reason="unreachable"}) rather than leaving pods
    # on an unreachable node.
    drain_stuck_timeout: float = 120.0
    # Admission cap per provisioner worker (batch window + overflow
    # backlog). Past it, adds are REFUSED back onto selection's backoff
    # requeue (counted on provision_backpressure_total{reason="queue-full"})
    # instead of growing the overflow without bound — the overload story's
    # bounded-admission layer (docs/design/overload.md and the
    # operations.md "saturation" runbook).
    provision_queue_max_pods: int = 50_000

    def _kube_retry_errors(self) -> List[str]:
        """Retry-envelope flag validation (kubeapi/client.py RetryPolicy)."""
        errors: List[str] = []
        if self.kube_retry_max_attempts < 1:
            errors.append(
                f"kube-retry-max-attempts must be >= 1, got {self.kube_retry_max_attempts}"
            )
        if self.kube_retry_backoff_base <= 0:
            errors.append(
                f"kube-retry-backoff-base must be > 0, got {self.kube_retry_backoff_base}"
            )
        if self.kube_retry_backoff_cap < self.kube_retry_backoff_base:
            errors.append(
                "kube-retry-backoff-cap must be >= kube-retry-backoff-base, got "
                f"{self.kube_retry_backoff_cap}"
            )
        if self.kube_watch_idle_timeout <= 0:
            errors.append(
                f"kube-watch-idle-timeout must be > 0, got {self.kube_watch_idle_timeout}"
            )
        return errors

    def validate(self) -> None:
        errors: List[str] = self._kube_retry_errors()
        if not self.cluster_name:
            errors.append("CLUSTER_NAME is required")
        if self.metrics_port == self.health_probe_port:
            errors.append("metrics and health ports must differ")
        if self.solver not in ("cost", "ffd", "greedy", "native", "remote"):
            errors.append(f"unknown solver {self.solver!r}")
        if self.solver == "remote" and not self.solver_endpoint:
            errors.append("solver=remote requires --solver-endpoint")
        if self.selection_concurrency < 1:
            errors.append(
                f"selection-concurrency must be >= 1, got {self.selection_concurrency}"
            )
        if not 0.0 < self.interruption_escalate_fraction <= 1.0:
            errors.append(
                "interruption-escalate-fraction must be in (0, 1], got "
                f"{self.interruption_escalate_fraction}"
            )
        errors.extend(self._scalar_errors())
        if self.cluster_store != "memory" and self.cluster_store != "incluster" and not self.cluster_store.startswith(
            ("http://", "https://")
        ):
            errors.append(
                f"cluster-store must be memory | incluster | URL, got {self.cluster_store!r}"
            )
        if errors:
            raise OptionsError("; ".join(errors))

    def _scalar_errors(self) -> List[str]:
        errors: List[str] = []
        # Non-negative scalars where 0 means "disabled": one data-driven
        # check so each new knob costs a row, not a branch.
        for flag, value in (
            ("slo-pending-p99", self.slo_pending_p99),
            ("slo-ttfl", self.slo_ttfl),
            ("consolidation-max-disruption", self.consolidation_max_disruption),
            ("consolidation-cooldown", self.consolidation_cooldown),
            ("disruption-budget", self.disruption_budget),
            ("drift-max-disruption", self.drift_max_disruption),
            ("reprice-debounce", self.reprice_debounce),
        ):
            if value < 0:
                errors.append(f"{flag} must be >= 0 (0 disables), got {value}")
        for flag, cap in (
            ("consolidation-max-disruption", self.consolidation_max_disruption),
            ("drift-max-disruption", self.drift_max_disruption),
        ):
            if cap > self.disruption_budget:
                errors.append(
                    f"{flag} must be <= disruption-budget "
                    f"({self.disruption_budget}) — a per-reason cap above the "
                    f"global budget can never be spent, got {cap}"
                )
        if self.reprice_threshold <= 0:
            errors.append(
                f"reprice-threshold must be > 0, got {self.reprice_threshold}"
            )
        if self.market_poll_interval < 0:
            errors.append(
                "market-poll-interval must be >= 0 (0 = provider default), "
                f"got {self.market_poll_interval}"
            )
        if not 0.0 < self.encode_compaction_threshold <= 1.0:
            errors.append(
                "encode-compaction-threshold must be in (0, 1], got "
                f"{self.encode_compaction_threshold}"
            )
        errors.extend(self._node_health_errors())
        from karpenter_tpu.controllers.provisioning import MAX_PODS_PER_BATCH

        if self.provision_queue_max_pods < MAX_PODS_PER_BATCH:
            errors.append(
                "provision-queue-max-pods must be >= one batch window "
                f"({MAX_PODS_PER_BATCH}) — a cap below it would refuse pods "
                "a single batch could absorb, got "
                f"{self.provision_queue_max_pods}"
            )
        return errors

    def _node_health_errors(self) -> List[str]:
        """Node-health timeout validation, including the ordering contract
        with the leaked-capacity GC (controllers/instancegc.py)."""
        from karpenter_tpu.controllers.instancegc import LAUNCH_GRACE_SECONDS

        errors: List[str] = []
        for flag, value in (
            ("node-unreachable-timeout", self.node_unreachable_timeout),
            ("node-liveness-timeout", self.node_liveness_timeout),
            ("drain-stuck-timeout", self.drain_stuck_timeout),
        ):
            if value <= 0:
                errors.append(f"{flag} must be > 0, got {value}")
        if 0 < self.node_liveness_timeout < LAUNCH_GRACE_SECONDS:
            errors.append(
                "node-liveness-timeout must be >= the instancegc launch "
                f"grace ({LAUNCH_GRACE_SECONDS:.0f}s) — deleting a "
                "never-joined node inside the bootstrap window races the "
                f"leak GC, got {self.node_liveness_timeout}"
            )
        if (
            self.node_unreachable_timeout > 0
            and self.node_liveness_timeout > 0
            and self.node_unreachable_timeout >= self.node_liveness_timeout
        ):
            errors.append(
                "node-unreachable-timeout must be < node-liveness-timeout "
                "(gone-dark detection is the fast path), got "
                f"{self.node_unreachable_timeout} >= {self.node_liveness_timeout}"
            )
        return errors


def parse(argv: Optional[List[str]] = None) -> Options:
    parser = argparse.ArgumentParser(prog="karpenter-tpu")
    parser.add_argument("--cluster-name", default=_env("CLUSTER_NAME", ""))
    parser.add_argument("--cluster-endpoint", default=_env("CLUSTER_ENDPOINT", ""))
    parser.add_argument("--metrics-port", type=int, default=int(_env("METRICS_PORT", "8080")))
    parser.add_argument(
        "--health-probe-port", type=int, default=int(_env("HEALTH_PROBE_PORT", "8081"))
    )
    parser.add_argument(
        "--kube-client-qps", type=float, default=float(_env("KUBE_CLIENT_QPS", "200"))
    )
    parser.add_argument(
        "--kube-client-burst", type=int, default=int(_env("KUBE_CLIENT_BURST", "300"))
    )
    parser.add_argument(
        "--kube-retry-max-attempts", type=int,
        default=int(_env("KUBE_RETRY_MAX_ATTEMPTS", "5")),
    )
    parser.add_argument(
        "--kube-retry-backoff-base", type=float,
        default=float(_env("KUBE_RETRY_BACKOFF_BASE", "0.1")),
    )
    parser.add_argument(
        "--kube-retry-backoff-cap", type=float,
        default=float(_env("KUBE_RETRY_BACKOFF_CAP", "5.0")),
    )
    parser.add_argument(
        "--kube-watch-idle-timeout", type=float,
        default=float(_env("KUBE_WATCH_IDLE_TIMEOUT", "300")),
    )
    parser.add_argument("--solver", default=_env("KARPENTER_SOLVER", "cost"))
    parser.add_argument(
        "--solver-endpoint", default=_env("KARPENTER_SOLVER_ENDPOINT", "")
    )
    parser.add_argument("--cloud-provider", default=_env("CLOUD_PROVIDER", "fake"))
    parser.add_argument(
        "--no-leader-election", action="store_true",
        default=_env("LEADER_ELECTION", "true").lower() == "false",
    )
    parser.add_argument("--log-level", default=_env("LOG_LEVEL", "info"))
    parser.add_argument(
        "--cluster-store", default=_env("CLUSTER_STORE", "memory")
    )
    parser.add_argument(
        "--selection-concurrency", type=int,
        default=int(_env("SELECTION_CONCURRENCY", "8")),
    )
    parser.add_argument(
        "--interruption-escalate-fraction", type=float,
        default=float(_env("INTERRUPTION_ESCALATE_FRACTION", "0.5")),
    )
    parser.add_argument(
        "--consolidation-max-disruption", type=int,
        default=int(_env("CONSOLIDATION_MAX_DISRUPTION", "1")),
    )
    parser.add_argument(
        "--consolidation-cooldown", type=float,
        default=float(_env("CONSOLIDATION_COOLDOWN", "60")),
    )
    parser.add_argument(
        "--disruption-budget", type=int,
        default=int(_env("DISRUPTION_BUDGET", "10")),
    )
    parser.add_argument(
        "--no-drift", action="store_true",
        default=_env("DRIFT_ENABLED", "true").lower() == "false",
    )
    parser.add_argument(
        "--drift-max-disruption", type=int,
        default=int(_env("DRIFT_MAX_DISRUPTION", "2")),
    )
    parser.add_argument(
        "--encode-compaction-threshold", type=float,
        default=float(_env("ENCODE_COMPACTION_THRESHOLD", "0.5")),
    )
    parser.add_argument(
        "--reprice-threshold", type=float,
        default=float(_env("REPRICE_THRESHOLD", "0.1")),
    )
    parser.add_argument(
        "--reprice-debounce", type=float,
        default=float(_env("REPRICE_DEBOUNCE", "5")),
    )
    parser.add_argument(
        "--market-poll-interval", type=float,
        default=float(_env("MARKET_POLL_INTERVAL", "0")),
    )
    parser.add_argument(
        "--slo-pending-p99", type=float,
        default=float(_env("SLO_PENDING_P99", "0")),
    )
    parser.add_argument(
        "--slo-ttfl", type=float,
        default=float(_env("SLO_TTFL", "0")),
    )
    parser.add_argument(
        "--node-unreachable-timeout", type=float,
        default=float(_env("NODE_UNREACHABLE_TIMEOUT", "60")),
    )
    parser.add_argument(
        "--node-liveness-timeout", type=float,
        default=float(_env("NODE_LIVENESS_TIMEOUT", "900")),
    )
    parser.add_argument(
        "--drain-stuck-timeout", type=float,
        default=float(_env("DRAIN_STUCK_TIMEOUT", "120")),
    )
    parser.add_argument(
        "--provision-queue-max-pods", type=int,
        default=int(_env("PROVISION_QUEUE_MAX_PODS", "50000")),
    )
    args = parser.parse_args(argv)
    options = Options(
        cluster_name=args.cluster_name,
        cluster_endpoint=args.cluster_endpoint,
        metrics_port=args.metrics_port,
        health_probe_port=args.health_probe_port,
        kube_client_qps=args.kube_client_qps,
        kube_client_burst=args.kube_client_burst,
        kube_retry_max_attempts=args.kube_retry_max_attempts,
        kube_retry_backoff_base=args.kube_retry_backoff_base,
        kube_retry_backoff_cap=args.kube_retry_backoff_cap,
        kube_watch_idle_timeout=args.kube_watch_idle_timeout,
        solver=args.solver,
        solver_endpoint=args.solver_endpoint,
        cloud_provider=args.cloud_provider,
        leader_election=not args.no_leader_election,
        log_level=args.log_level,
        cluster_store=args.cluster_store,
        selection_concurrency=args.selection_concurrency,
        interruption_escalate_fraction=args.interruption_escalate_fraction,
        consolidation_max_disruption=args.consolidation_max_disruption,
        consolidation_cooldown=args.consolidation_cooldown,
        disruption_budget=args.disruption_budget,
        drift_enabled=not args.no_drift,
        drift_max_disruption=args.drift_max_disruption,
        encode_compaction_threshold=args.encode_compaction_threshold,
        slo_pending_p99=args.slo_pending_p99,
        slo_ttfl=args.slo_ttfl,
        reprice_threshold=args.reprice_threshold,
        reprice_debounce=args.reprice_debounce,
        market_poll_interval=args.market_poll_interval,
        node_unreachable_timeout=args.node_unreachable_timeout,
        node_liveness_timeout=args.node_liveness_timeout,
        drain_stuck_timeout=args.drain_stuck_timeout,
        provision_queue_max_pods=args.provision_queue_max_pods,
    )
    options.validate()
    return options


# The subset of Options safe to change on a LIVE process: fields that are
# read at use time rather than baked into constructed objects. Everything
# else (ports, store backend, solver, concurrency envelopes) is wired into
# threads and sockets at boot and only a restart can change it. SIGHUP and
# POST /debug/loglevel both route through apply_reload so the two paths
# can't drift (cmd/controller.py, runtime._HTTPHandler).
RELOADABLE = ("log_level", "slo_pending_p99", "slo_ttfl")


def apply_reload(live: Options, fresh: Options) -> dict:
    """Copy the RELOADABLE fields of `fresh` (a re-parse of the original
    argv, which re-reads env fallbacks too) onto the live Options; returns
    {field: new_value} for what actually changed — the input
    Manager.reload_options applies."""
    changed = {}
    for name in RELOADABLE:
        new = getattr(fresh, name)
        if getattr(live, name) != new:
            setattr(live, name, new)
            changed[name] = new
    return changed
