"""Small shared utilities (ref: pkg/utils/*)."""
