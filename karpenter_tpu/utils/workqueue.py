"""Rate-limited work queues.

Ref: pkg/utils/parallel/workqueue.go (token-bucket async task runner used to
throttle CreateFleet) and termination/eviction.go (set-deduped queue with
exponential per-item backoff 100ms -> 10s).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Dict, Hashable, Optional

from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK


class RateLimiter:
    """Token bucket: qps refill, burst capacity (ref: client-go flowcontrol
    as used at aws/cloudprovider.go:41-46)."""

    def __init__(self, qps: float, burst: int, clock: Optional[Clock] = None):
        self.qps = qps
        self.burst = burst
        self.clock = clock or SYSTEM_CLOCK
        self._tokens = float(burst)  # vet: guarded-by(self._lock)
        self._last = self.clock.now()  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()

    def try_acquire(self) -> bool:
        with self._lock:
            now = self.clock.now()
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def wait_time(self) -> float:
        with self._lock:
            if self._tokens >= 1.0:
                return 0.0
            return (1.0 - self._tokens) / self.qps


class BackoffQueue:
    """Set-deduped retry queue with per-item exponential backoff
    (ref: termination/eviction.go:33-54). Synchronous drain model: callers
    pump `process(fn)`; items whose fn returns False are requeued with
    backoff. Tests drive it with a FakeClock."""

    def __init__(
        self,
        base_delay: float = 0.1,
        max_delay: float = 10.0,
        clock: Optional[Clock] = None,
        max_items: Optional[int] = None,
    ):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.clock = clock or SYSTEM_CLOCK
        # Requeue-set bound: past this many distinct in-flight items, add()
        # refuses new ones (requeues of items already held always land —
        # dropping an accepted item's retry would strand it). None =
        # unbounded, for queues whose feeder is itself bounded.
        self.max_items = max_items
        self._queue: deque = deque()  # vet: guarded-by(self._lock)
        self._in_queue: set = set()  # vet: guarded-by(self._lock)
        self._failures: Dict[Hashable, int] = {}  # vet: guarded-by(self._lock)
        self._not_before: Dict[Hashable, float] = {}  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()

    def add(self, item: Hashable) -> bool:
        with self._lock:
            if item in self._in_queue:
                return False
            if self.max_items is not None and len(self._in_queue) >= self.max_items:
                return False
            self._in_queue.add(item)
            self._queue.append(item)
            return True

    def __len__(self):
        return len(self._queue)  # vet: unguarded(GIL-atomic len; monitoring read)

    def __contains__(self, item):
        return item in self._in_queue  # vet: unguarded(GIL-atomic membership; monitoring read)

    def process(self, fn: Callable[[Hashable], bool]) -> int:
        """Run fn over every currently-due item once. Returns #successes.
        Failures requeue with exponential backoff."""
        with self._lock:
            batch = list(self._queue)
            self._queue.clear()
            # Snapshot the due times with the batch: reading them item-by-
            # item outside the lock raced a concurrent process() call's
            # backoff writes (found by the vet lock-discipline checker).
            not_before = dict(self._not_before)
        done = 0
        now = self.clock.now()
        for item in batch:
            if not_before.get(item, 0.0) > now:
                with self._lock:
                    self._queue.append(item)
                continue
            ok = fn(item)
            with self._lock:
                if ok:
                    self._in_queue.discard(item)
                    self._failures.pop(item, None)
                    self._not_before.pop(item, None)
                    done += 1
                else:
                    failures = self._failures.get(item, 0) + 1
                    self._failures[item] = failures
                    delay = min(self.base_delay * (2 ** (failures - 1)), self.max_delay)
                    self._not_before[item] = now + delay
                    self._queue.append(item)
        return done
