"""Lightweight sampling profiler behind /debug/stacks and the threaded
Manager benchmarks.

Samples sys._current_frames() on an interval and aggregates per-thread
leaf frames plus whole-stack signatures, so we can see where wall-clock
goes across the watch pumps / selection loop / batcher / bind threads
without yappi (not in this image). Lives in the production package — the
/debug/stacks endpoint must not degrade when a deployment ships
karpenter_tpu without the repo's tools/ tree.
"""

from __future__ import annotations

import collections
import sys
import threading

from karpenter_tpu.utils.clock import SYSTEM_CLOCK


class StackProf:
    def __init__(self, interval_s: float = 0.004):
        self.interval_s = interval_s
        self.leaf = collections.Counter()
        self.frames2 = collections.Counter()  # leaf + caller, per thread-name
        self.samples = 0
        self._stop = threading.Event()
        self._thread = None

    def _run(self):
        while not self._stop.is_set():
            names = {t.ident: t.name for t in threading.enumerate()}
            for ident, frame in sys._current_frames().items():
                if ident == self._thread.ident:
                    continue
                name = names.get(ident, str(ident))
                # collapse thread pools into one bucket
                base = name.rstrip("0123456789-_ ")
                f = frame
                leaf = f"{f.f_code.co_filename.split('/')[-1]}:{f.f_code.co_name}"
                caller = ""
                if f.f_back is not None:
                    b = f.f_back
                    caller = f"{b.f_code.co_filename.split('/')[-1]}:{b.f_code.co_name}"
                self.leaf[(base, leaf)] += 1
                self.frames2[(base, f"{caller} -> {leaf}")] += 1
            self.samples += 1
            SYSTEM_CLOCK.sleep(self.interval_s)

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True, name="stackprof")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join()

    def report(self, top=30):
        print(f"== {self.samples} samples ==")
        print("-- by (thread, caller -> leaf) --")
        for (tname, sig), n in self.frames2.most_common(top):
            print(f"{n:6d}  [{tname}] {sig}")
