"""Pod-latency SLO pipeline: phase-attributed lifecycle tracking, a rolling
SLO evaluator, and a flight recorder.

The reference ships only aggregate Prometheus duration histograms
(SURVEY.md §5): when a p99 regresses there is no way to tell WHICH hop of
the provisioning pipeline ate the budget, and when a storm smoke fails the
only forensic record is the log tail. This module closes both gaps:

- ``PodLifecycleTracker`` stamps monotonic phase transitions per pod —
  unschedulable-seen → batched → constraint-compiled → solve-dispatched →
  solve-fetched → launched → node-ready → bound — into bounded per-phase
  histograms (``pod_phase_seconds{phase}``) plus an end-to-end
  ``pod_pending_seconds`` histogram. It is fed from the store's verb-level
  watch-delta feed (O(churn) per sweep, the same feed the incremental
  encoder rides) plus explicit stamps at the pipeline's own commit points,
  and survives controller restarts by re-anchoring on the pod's
  creationTimestamp: a tracker that first sees a pod mid-flight anchors its
  pending clock at creation, not at process boot, so restart-spanning
  latency is charged honestly.

  Phase semantics: each stamp attributes the time since the pod's PREVIOUS
  stamp to the stamped phase, whatever order events arrive in (binds land
  before node readiness on the launch path; the canonical order above is
  the attribution order, not a delivery contract). A stamp for a phase
  already recorded this pending cycle is ignored (monotonic); a
  ``reschedule`` verb starts a fresh cycle.

- ``SloEvaluator`` keeps rolling windows of end-to-end pending times,
  time-to-first-launch, and per-phase durations; publishes
  ``slo_p99_pending_seconds`` / ``slo_p99_ttfl_seconds`` gauges; and, when
  a configured target (``--slo-pending-p99`` / ``--slo-ttfl``) is
  exceeded, counts ``slo_breaches_total{slo}``, records a breach event
  naming the worst offending pods and their slowest phase, and triggers a
  flight-recorder dump.

- ``FlightRecorder`` is a lock-annotated bounded ring of structured
  decision/fault events: launch decisions (chosen type + price +
  relaxation level), kube-API retries, faultpoint hits, chip quarantines,
  drains, consolidation actions, SLO breaches. It dumps as JSON on SLO
  breach, on crash (crashpoint hook + atexit), and on demand via the
  runtime's ``/debug/flightrecorder`` endpoint. Events carry a strictly
  increasing ``seq``; ``dropped`` counts ring evictions, so a dump with
  ``dropped == 0`` is gap-free by construction — the storm smokes assert
  exactly that.

Set ``KARPENTER_FLIGHT_DIR`` to make breach/crash/exit dumps land on disk;
without it, dumps are only served over HTTP. See
docs/design/observability.md for the phase model and SLO semantics.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from karpenter_tpu.utils import crashpoints
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK
from karpenter_tpu.utils.metrics import DURATION_BUCKETS, REGISTRY

log = klog.named("obs")

# The canonical phase (attribution) order. Every phase gets a
# pod_phase_seconds{phase} series; the chaos smoke asserts all of them
# publish under storm load.
PHASES = (
    "unschedulable-seen",
    "batched",
    "constraint-compiled",
    "solve-dispatched",
    "solve-fetched",
    "launched",
    "node-ready",
    "bound",
)

# The default 5ms-60s DURATION_BUCKETS saturate exactly where a pending-time
# breach lives (storm targets run 60-240s; a wedged pod pends for minutes) —
# the exposed histograms must resolve the SLO regime or dashboard quantiles
# cap at 60s while the in-process evaluator sees the truth.
PENDING_BUCKETS = DURATION_BUCKETS + (
    90.0, 120.0, 180.0, 240.0, 300.0, 450.0, 600.0,
)

POD_PHASE_SECONDS = REGISTRY.histogram(
    "pod_phase_seconds",
    "Time attributed to each pod lifecycle phase (see "
    "docs/design/observability.md for the phase model)",
    ["phase"],
    buckets=PENDING_BUCKETS,
)
POD_PENDING_SECONDS = REGISTRY.histogram(
    "pod_pending_seconds",
    "End-to-end pod pending time: creation/unschedulable-seen to bound",
    buckets=PENDING_BUCKETS,
)
SLO_P99_PENDING = REGISTRY.gauge(
    "slo_p99_pending_seconds",
    "Rolling-window p99 of pod_pending_seconds (the sustained-churn SLO "
    "signal; target via --slo-pending-p99)",
)
SLO_P99_TTFL = REGISTRY.gauge(
    "slo_p99_ttfl_seconds",
    "Rolling-window p99 of time-to-first-launch (unschedulable-seen to "
    "node launch; target via --slo-ttfl)",
)
SLO_BREACHES_TOTAL = REGISTRY.counter(
    "slo_breaches_total",
    "SLO breach episodes by objective (each one triggers a flight-recorder "
    "dump)",
    ["slo"],
)
FLIGHT_EVENTS_TOTAL = REGISTRY.counter(
    "flight_recorder_events_total",
    "Flight-recorder events recorded, by kind",
    ["kind"],
)
TRACKED_PODS = REGISTRY.gauge(
    "lifecycle_tracked_pods",
    "Pods currently tracked by the lifecycle tracker (bounded; evictions "
    "count as forgotten)",
)


def _quantile(values: Sequence[float], q: float) -> float:
    """Nearest-rank quantile over an unsorted sample list (0.0 if empty)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(1, int(math.ceil(q * len(ordered))))
    return ordered[min(rank, len(ordered)) - 1]


class FlightRecorder:
    """Bounded ring of structured decision/fault events (see module
    docstring). record() is cheap (one deque append under a short lock) so
    call sites can stay on hot paths; serialization happens only at dump
    time, on a consistent snapshot."""

    MAXLEN = 8192

    def __init__(self, clock: Optional[Clock] = None, maxlen: int = MAXLEN):
        self.clock = clock or SYSTEM_CLOCK
        self._events: deque = deque(maxlen=maxlen)  # vet: guarded-by(self._lock)
        self._seq = 0  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()

    def configure(self, clock: Optional[Clock] = None) -> None:
        if clock is not None:
            self.clock = clock

    def record(self, kind: str, **fields) -> None:
        event = {
            "kind": kind,
            "t_wall": self.clock.now(),
            "t_mono": time.perf_counter(),
            **fields,
        }
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._events.append(event)
        FLIGHT_EVENTS_TOTAL.inc(kind)

    def snapshot(self) -> dict:
        """Consistent view: events copied under the lock, with enough
        metadata (seq / dropped) for a reader to prove the record gap-free."""
        with self._lock:
            events = list(self._events)
            seq = self._seq
        return {
            "pid": os.getpid(),
            "seq": seq,
            "events": events,
            # Ring evictions since process start: a dump with dropped == 0
            # contains EVERY event ever recorded — the storm smokes' no-
            # unexplained-gaps oracle.
            "dropped": seq - len(events),
            "first_seq": events[0]["seq"] if events else 0,
            "last_seq": events[-1]["seq"] if events else 0,
        }

    def dump_json(self) -> str:
        return json.dumps(self.snapshot(), default=str)

    def dump(self, tag: str = "manual") -> Optional[str]:
        """Write a dump file into KARPENTER_FLIGHT_DIR (None when unset —
        the HTTP endpoint is then the only reader)."""
        directory = os.environ.get("KARPENTER_FLIGHT_DIR")
        if not directory:
            return None
        path = os.path.join(
            directory, f"flightrecorder-{tag}-{os.getpid()}.json"
        )
        try:
            with open(path, "w") as f:
                f.write(self.dump_json())
        except OSError:
            log.exception("flight-recorder dump to %s failed", path)
            return None
        return path

    def count(self, kind: Optional[str] = None) -> int:
        with self._lock:
            if kind is None:
                return len(self._events)
            return sum(1 for e in self._events if e["kind"] == kind)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._seq = 0


RECORDER = FlightRecorder()


class SloEvaluator:
    """Rolling-window SLO evaluation over the tracker's samples. Quantiles
    recompute at most once per EVAL_INTERVAL_S (the windows absorb storm
    rates without per-sample sorts); breaches are episode-gated so a
    sustained violation produces one dump per cooldown, not one per pod."""

    WINDOW_SECONDS = 300.0
    MAX_SAMPLES = 8192
    EVAL_INTERVAL_S = 1.0
    BREACH_COOLDOWN_S = 30.0
    OFFENDERS = 5

    def __init__(self, clock: Optional[Clock] = None, recorder: Optional[FlightRecorder] = None):
        self.clock = clock or SYSTEM_CLOCK
        self.recorder = recorder or RECORDER
        # Targets: 0 disables the objective (Options defaults — production
        # wiring passes --slo-pending-p99 / --slo-ttfl through Manager).
        self.pending_p99_target = 0.0
        self.ttfl_target = 0.0
        self._lock = threading.Lock()
        # (t, seconds, uid, slowest_phase) samples
        self._pending: deque = deque(maxlen=self.MAX_SAMPLES)  # vet: guarded-by(self._lock)
        self._ttfl: deque = deque(maxlen=self.MAX_SAMPLES)  # vet: guarded-by(self._lock)
        self._phases: Dict[str, deque] = {  # vet: guarded-by(self._lock)
            phase: deque(maxlen=2048) for phase in PHASES
        }
        self._last_eval = -float("inf")  # vet: guarded-by(self._lock)
        self._last_breach: Dict[str, float] = {}  # vet: guarded-by(self._lock)
        self.breaches: Dict[str, int] = {}  # vet: guarded-by(self._lock)

    def configure(
        self,
        clock: Optional[Clock] = None,
        pending_p99_target: Optional[float] = None,
        ttfl_target: Optional[float] = None,
    ) -> None:
        if clock is not None:
            self.clock = clock
        if pending_p99_target is not None:
            self.pending_p99_target = pending_p99_target
        if ttfl_target is not None:
            self.ttfl_target = ttfl_target

    # -- sample feeds (called by the tracker) --------------------------------

    def add_pending(self, seconds: float, uid: str, slowest_phase: str) -> None:
        now = self.clock.now()
        with self._lock:
            self._pending.append((now, seconds, uid, slowest_phase))
        self.evaluate()

    def add_ttfl(self, seconds: float, uid: str) -> None:
        now = self.clock.now()
        with self._lock:
            self._ttfl.append((now, seconds, uid, ""))
        self.evaluate()

    def add_phase(self, phase: str, seconds: float) -> None:
        now = self.clock.now()
        with self._lock:
            window = self._phases.get(phase)
            if window is not None:
                window.append((now, seconds))

    def add_phase_many(self, phase: str, durations: Sequence[float]) -> None:
        now = self.clock.now()
        with self._lock:
            window = self._phases.get(phase)
            if window is not None:
                window.extend((now, s) for s in durations)

    # -- evaluation ----------------------------------------------------------

    def _window_values(self, samples: deque, now: float) -> List[float]:
        """Values inside the rolling window (caller holds the lock).
        Expired leading samples are evicted in place."""
        horizon = now - self.WINDOW_SECONDS
        while samples and samples[0][0] < horizon:
            samples.popleft()
        return [s[1] for s in samples]

    def evaluate(self, force: bool = False) -> dict:
        """Recompute quantiles (clock-gated unless forced), publish gauges,
        and fire breach handling; returns the /debug/slo snapshot."""
        now = self.clock.now()
        with self._lock:
            if not force and now - self._last_eval < self.EVAL_INTERVAL_S:
                return {}
            self._last_eval = now
            pending = self._window_values(self._pending, now)
            ttfl = self._window_values(self._ttfl, now)
            phases = {
                phase: self._window_values(window, now)
                for phase, window in self._phases.items()
            }
            breaches = dict(self.breaches)
        pending_p99 = _quantile(pending, 0.99)
        ttfl_p99 = _quantile(ttfl, 0.99)
        SLO_P99_PENDING.set(pending_p99)
        SLO_P99_TTFL.set(ttfl_p99)
        snapshot = {
            "targets": {
                "pending-p99": self.pending_p99_target,
                "ttfl": self.ttfl_target,
            },
            "pending": {
                "count": len(pending),
                "p50": _quantile(pending, 0.50),
                "p99": pending_p99,
            },
            "ttfl": {
                "count": len(ttfl),
                "p50": _quantile(ttfl, 0.50),
                "p99": ttfl_p99,
            },
            "phases": {
                phase: {
                    "count": len(values),
                    "p50": _quantile(values, 0.50),
                    "p99": _quantile(values, 0.99),
                }
                for phase, values in phases.items()
            },
            "breaches": breaches,
        }
        pending_breach = (
            self.pending_p99_target > 0 and pending_p99 > self.pending_p99_target
        )
        ttfl_breach = self.ttfl_target > 0 and ttfl_p99 > self.ttfl_target
        if pending_breach or ttfl_breach:
            # Offenders cost a full window sort — pay for it only when a
            # breach actually fires, never on the steady-state eval path.
            with self._lock:
                offenders = self._offenders_locked(now)
            if pending_breach:
                self._breach(
                    "pending-p99", pending_p99, self.pending_p99_target, offenders
                )
            if ttfl_breach:
                self._breach("ttfl", ttfl_p99, self.ttfl_target, offenders)
            # The call that DETECTS a breach must also report it — the
            # counts snapshotted above predate the check.
            with self._lock:
                snapshot["breaches"] = dict(self.breaches)
        return snapshot

    def _offenders_locked(self, now: float) -> List[dict]:
        """Worst pending samples in the window — the pods a breach dump
        names, each with its slowest phase (caller holds the lock)."""
        horizon = now - self.WINDOW_SECONDS
        worst = sorted(
            (s for s in self._pending if s[0] >= horizon),
            key=lambda s: -s[1],
        )[: self.OFFENDERS]
        return [
            {"pod_uid": uid, "pending_seconds": seconds, "slowest_phase": phase}
            for (_, seconds, uid, phase) in worst
        ]

    def _breach(self, slo: str, observed: float, target: float, offenders) -> None:
        now = self.clock.now()
        with self._lock:
            if now - self._last_breach.get(slo, -float("inf")) < self.BREACH_COOLDOWN_S:
                return
            self._last_breach[slo] = now
            self.breaches[slo] = self.breaches.get(slo, 0) + 1
        SLO_BREACHES_TOTAL.inc(slo)
        log.warning(
            "SLO breach: %s p99 %.3fs > target %.3fs (%d offender(s) named "
            "in the flight-recorder dump)", slo, observed, target, len(offenders),
        )
        self.recorder.record(
            "slo-breach", slo=slo, observed_p99=observed, target=target,
            offenders=offenders,
        )
        self.recorder.dump(tag=f"slo-{slo}")


class _Entry:
    __slots__ = ("anchor", "last", "stamps")

    def __init__(self, anchor: float):
        self.anchor = anchor
        self.last = anchor
        self.stamps: Dict[str, float] = {}


class PodLifecycleTracker:
    """Per-pod phase stamping (see module docstring). One process-wide
    instance (OBS) mirrors metrics.REGISTRY / tracing.TRACER; Manager
    configures its clock + SLO targets and attaches it to the cluster
    store's watch-delta feed."""

    MAX_TRACKED = 131072
    TERMINAL = frozenset(("bound", "node-ready"))

    def __init__(self, clock: Optional[Clock] = None):
        self.clock = clock or SYSTEM_CLOCK
        self.evaluator = SloEvaluator(clock=self.clock)
        self._pods: Dict[str, _Entry] = {}  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()
        self._source = None  # the attached cluster store (latest attach wins)

    def configure(
        self,
        clock: Optional[Clock] = None,
        slo_pending_p99: Optional[float] = None,
        slo_ttfl: Optional[float] = None,
    ) -> None:
        if clock is not None:
            self.clock = clock
        self.evaluator.configure(
            clock=clock,
            pending_p99_target=slo_pending_p99,
            ttfl_target=slo_ttfl,
        )

    def attach(self, cluster) -> None:
        """Subscribe to `cluster`'s verb-level watch feed. The newest attach
        wins: stores have no unsubscribe, so the callback closes over its
        cluster and goes inert when a newer one is attached (chaos harnesses
        rebuild the 'controller process' — and its store — mid-storm)."""
        self._source = cluster

        def _on_delta(verb: str, kind: str, obj, _cluster=cluster) -> None:
            if self._source is _cluster:
                self.on_delta(verb, kind, obj)

        cluster.watch_deltas(_on_delta)

    # -- the watch-delta feed ------------------------------------------------

    def on_delta(self, verb: str, kind: str, obj) -> None:
        if kind != "pod":
            return
        if verb == "delete":
            self.forget(obj.uid)
        elif verb == "reschedule":
            self.reanchor(obj.uid)
        elif verb == "bind":
            self._on_bound(obj, reanchor=True)
        elif obj.node_name:
            # apply/update of an already-bound pod (watch re-list, restart
            # catch-up): counts as bound only for a pod tracked as pending.
            self._on_bound(obj, reanchor=False)
        elif obj.is_provisionable():
            self.first_seen(obj)

    def _on_bound(self, pod, reanchor: bool) -> None:
        uid = pod.uid
        with self._lock:
            entry = self._pods.get(uid)
        if entry is None:
            created = getattr(pod, "created_at", None)
            if not reanchor or created is None:
                # A re-list apply of an already-bound pod we never saw
                # pending: it bound before this tracker watched (or while
                # the controller was down) — creation→now would charge its
                # whole AGE as pending, so nothing honest can be recorded.
                return
            # Restart re-anchor: the pod's actual BIND event arrived for a
            # pod this tracker never saw pending (it was pending across the
            # restart, relisted mid-race); charge from creationTimestamp.
            self.first_seen(pod)
        # A pod binding onto an already-Ready node never gets a Readiness
        # stamp; record the node-ready edge here so the phase publishes.
        source = self._source
        if source is not None and pod.node_name:
            try:
                node = source.try_get_node(pod.node_name)
            except Exception:  # noqa: BLE001 — store teardown race, stamp anyway
                node = None
            if node is not None and getattr(node, "ready", False):
                self.stamp(uid, "node-ready")
        self.stamp(uid, "bound")

    # -- stamping ------------------------------------------------------------

    def first_seen(self, pod) -> None:
        """Begin (or refresh) tracking: anchor at creationTimestamp when the
        store stamped one (restart re-anchoring), else at now."""
        now = self.clock.now()
        uid = pod.uid
        with self._lock:
            if uid in self._pods:
                return
            anchor = getattr(pod, "created_at", None)
            if anchor is None or anchor > now:
                anchor = now
            self._ensure_room_locked()
            entry = self._pods[uid] = _Entry(anchor)
            entry.stamps["unschedulable-seen"] = now
            entry.last = now
            tracked = len(self._pods)
        TRACKED_PODS.set(float(tracked))
        delta = max(0.0, now - entry.anchor)
        POD_PHASE_SECONDS.observe(delta, "unschedulable-seen")
        self.evaluator.add_phase("unschedulable-seen", delta)

    def _ensure_room_locked(self) -> None:
        # Bounded memory: evict the longest-tracked entry (dict preserves
        # insertion order). A 131k backlog overflow loses the OLDEST pods'
        # samples, never the live churn.
        while len(self._pods) >= self.MAX_TRACKED:
            self._pods.pop(next(iter(self._pods)))

    def stamp(self, uid: str, phase: str) -> None:
        """Attribute now - (pod's previous stamp) to `phase`. Unknown pods
        and repeat stamps are ignored (monotonic per pending cycle)."""
        now = self.clock.now()
        with self._lock:
            entry = self._pods.get(uid)
            if entry is None or phase in entry.stamps:
                return
            entry.stamps[phase] = now
            delta = max(0.0, now - entry.last)
            entry.last = now
            anchor = entry.anchor
            retire = self.TERMINAL <= entry.stamps.keys()
            slowest = self._slowest_phase_locked(entry) if phase == "bound" else ""
            if retire:
                self._pods.pop(uid, None)
            tracked = len(self._pods)
        TRACKED_PODS.set(float(tracked))
        POD_PHASE_SECONDS.observe(delta, phase)
        self.evaluator.add_phase(phase, delta)
        if phase == "launched":
            self.evaluator.add_ttfl(max(0.0, now - anchor), uid)
        elif phase == "bound":
            pending = max(0.0, now - anchor)
            POD_PENDING_SECONDS.observe(pending)
            self.evaluator.add_pending(pending, uid, slowest)

    def stamp_many(self, uids: Sequence[str], phase: str) -> None:
        """One lock round + batched histogram observes for a whole schedule
        (the provisioning pass stamps thousands of pods per phase edge;
        per-pod locking here would convoy the storm path the same way
        per-key metrics locking convoyed the reconcile pools)."""
        if not uids:
            return
        now = self.clock.now()
        deltas: List[float] = []
        finals: List[tuple] = []  # (uid, anchor, slowest) for bound/launched
        with self._lock:
            for uid in uids:
                entry = self._pods.get(uid)
                if entry is None or phase in entry.stamps:
                    continue
                entry.stamps[phase] = now
                deltas.append(max(0.0, now - entry.last))
                entry.last = now
                if phase in ("launched", "bound"):
                    slowest = (
                        self._slowest_phase_locked(entry)
                        if phase == "bound"
                        else ""
                    )
                    finals.append((uid, entry.anchor, slowest))
                if self.TERMINAL <= entry.stamps.keys():
                    self._pods.pop(uid, None)
            tracked = len(self._pods)
        TRACKED_PODS.set(float(tracked))
        if deltas:
            POD_PHASE_SECONDS.observe_many(deltas, phase)
            self.evaluator.add_phase_many(phase, deltas)
        for uid, anchor, slowest in finals:
            if phase == "launched":
                self.evaluator.add_ttfl(max(0.0, now - anchor), uid)
            else:
                pending = max(0.0, now - anchor)
                POD_PENDING_SECONDS.observe(pending)
                self.evaluator.add_pending(pending, uid, slowest)

    @staticmethod
    def _slowest_phase_locked(entry: _Entry) -> str:
        """The phase that ate the most of this pod's pending time — what a
        breach dump attributes (caller holds the tracker lock)."""
        ordered = sorted(entry.stamps.items(), key=lambda kv: kv[1])
        slowest, worst = "", -1.0
        previous = entry.anchor
        for phase, at in ordered:
            duration = at - previous
            if duration > worst:
                slowest, worst = phase, duration
            previous = at
        return slowest

    def reanchor(self, uid: str) -> None:
        """A displaced pod re-enters pending: fresh cycle, anchor = now."""
        now = self.clock.now()
        with self._lock:
            self._ensure_room_locked()
            entry = self._pods[uid] = _Entry(now)
            entry.stamps["unschedulable-seen"] = now
            tracked = len(self._pods)
        TRACKED_PODS.set(float(tracked))
        POD_PHASE_SECONDS.observe(0.0, "unschedulable-seen")

    def forget(self, uid: str) -> None:
        with self._lock:
            self._pods.pop(uid, None)
            tracked = len(self._pods)
        TRACKED_PODS.set(float(tracked))

    def pending_anchors(self, uids: Sequence[str]) -> Dict[str, float]:
        """Pending-cycle anchor (epoch seconds) per tracked uid — one lock
        round for a whole backlog. Untracked uids are omitted; callers treat
        a missing anchor as "newest" (the provisioning worker's aging refill
        sorts by this, so an untracked pod can never starve a tracked one)."""
        with self._lock:
            pods = self._pods
            return {uid: pods[uid].anchor for uid in uids if uid in pods}

    def tracked(self) -> int:
        with self._lock:
            return len(self._pods)

    def reset(self) -> None:
        """Test hook: drop all per-pod state (histograms are global and
        stay, like every other REGISTRY metric)."""
        with self._lock:
            self._pods.clear()
        TRACKED_PODS.set(0.0)

    def slo_snapshot(self) -> dict:
        return self.evaluator.evaluate(force=True)


OBS = PodLifecycleTracker()
# The tracker's evaluator shares the process recorder so breach events and
# launch decisions interleave in one timeline.
OBS.evaluator.recorder = RECORDER


# -- stack dumps (/debug/stacks) ---------------------------------------------


def stacks_snapshot(sample_s: float = 0.2) -> dict:
    """Every thread's current stack plus a short sampled hot-path profile
    (StackProf-backed — the same sampler the benchmarks use)."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    threads = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, str(ident))
        threads[f"{name}-{ident}"] = traceback.format_stack(frame)
    hot: List[dict] = []
    samples = 0
    if sample_s > 0:
        from karpenter_tpu.utils.stackprof import StackProf

        profiler = StackProf(interval_s=0.004).start()
        SYSTEM_CLOCK.sleep(sample_s)
        profiler.stop()
        samples = profiler.samples
        hot = [
            {"thread": thread, "frame": sig, "count": count}
            for (thread, sig), count in profiler.frames2.most_common(20)
        ]
    return {
        "pid": os.getpid(),
        "thread_count": len(threads),
        "threads": threads,
        "profile_samples": samples,
        "hot": hot,
    }


# -- crash / exit dumps --------------------------------------------------------


def _on_crash(site: str) -> None:
    RECORDER.record("crash", site=site)
    RECORDER.dump(tag=f"crash-{site.replace('.', '-')}")


crashpoints.on_crash(_on_crash)

if os.environ.get("KARPENTER_FLIGHT_DIR"):
    import atexit

    atexit.register(RECORDER.dump, "exit")
