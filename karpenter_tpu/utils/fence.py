"""Write fencing by lease generation.

A deposed leader must not mutate shared state: its in-flight sweeps race the
successor's and can double-launch capacity or overwrite fresher bindings. The
fence is armed with the lease generation (the Lease's monotonic ``transitions``
counter) when leadership is acquired and revoked the instant the elector
observes leadership lost. Every mutating verb — store writes and cloud
launch/terminate — calls :meth:`WriteFence.check` first; once revoked the verb
raises :class:`FencedWriteError` instead of reaching either backend.

The fence also powers cooperative sweep abort: reconcile threads bind their
cluster's fence via :func:`bind_thread`, and a gate installed into
``utils.crashpoints`` re-checks it at every instrumented crashpoint site, so a
long sweep that straddles a leadership loss dies at the next site instead of
draining to completion.
"""
from __future__ import annotations

import threading
from typing import Optional

from karpenter_tpu.utils import crashpoints
from karpenter_tpu.utils.metrics import REGISTRY

LEADER_FENCE_REJECTED_TOTAL = REGISTRY.counter(
    "leader_fence_rejected_total",
    "Mutating verbs refused because the write fence was revoked (stale leader)",
    ["verb"],
)

_UNARMED = "unarmed"
_ACTIVE = "active"
_REVOKED = "revoked"


class FencedWriteError(Exception):
    """A mutating verb was refused because this process is no longer leader.

    Deliberately an ``Exception`` (not ``BaseException``): a fenced sweep must
    travel the same recovery paths as any other reconcile error so the loop
    records the failure and parks the key instead of killing the thread.
    """

    def __init__(self, verb: str, generation: Optional[int]):
        super().__init__(
            f"write fence revoked: refusing {verb} (lease generation {generation})"
        )
        self.verb = verb
        self.generation = generation


class WriteFence:
    """Tri-state fence: unarmed (pass-through) / active / revoked.

    Arm/revoke are keyed by holder identity so a rival elector sharing the
    store in-process (tests drive several electors over one Cluster) cannot
    revoke a fence it never armed.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._state = _UNARMED  # vet: guarded-by(self._lock)
        self._holder: Optional[str] = None  # vet: guarded-by(self._lock)
        self._generation: Optional[int] = None  # vet: guarded-by(self._lock)

    @property
    def generation(self) -> Optional[int]:
        with self._lock:
            return self._generation if self._state == _ACTIVE else None

    def arm(self, holder: str, generation: int) -> None:
        """Grant write access for ``holder`` at ``generation``. Idempotent;
        re-arming (renewal, or a fresh acquire after revocation) overwrites."""
        with self._lock:
            self._state = _ACTIVE
            self._holder = holder
            self._generation = int(generation)

    def revoke(self, holder: str) -> None:
        """Flip to revoked iff ``holder`` is the one the fence was armed for."""
        with self._lock:
            if self._state == _ACTIVE and self._holder == holder:
                self._state = _REVOKED

    def disarm(self, holder: str) -> None:
        """Voluntary release: return to pass-through (clean shutdown path)."""
        with self._lock:
            if self._holder == holder:
                self._state = _UNARMED
                self._holder = None
                self._generation = None

    def check(self, verb: str) -> None:
        """Refuse ``verb`` with :class:`FencedWriteError` once revoked."""
        with self._lock:
            if self._state != _REVOKED:
                return
            generation = self._generation
        LEADER_FENCE_REJECTED_TOTAL.inc(verb)
        from karpenter_tpu.utils.obs import RECORDER

        RECORDER.record("fence-reject", verb=verb, generation=generation)
        raise FencedWriteError(verb, generation)

    def revoked(self) -> bool:
        with self._lock:
            return self._state == _REVOKED


_thread_state = threading.local()


def bind_thread(fence: Optional[WriteFence]) -> None:
    """Associate ``fence`` with the calling thread for cooperative abort."""
    _thread_state.fence = fence


def current_thread_fence() -> Optional[WriteFence]:
    return getattr(_thread_state, "fence", None)


def _abort_gate(site: str) -> None:
    """Crashpoint gate: abort a deposed leader's sweep at the next site."""
    fence = current_thread_fence()
    if fence is not None and fence.revoked():
        fence.check(f"sweep:{site}")


crashpoints.set_abort_gate(_abort_gate)
