"""Named fault-injection sites for chaos-testing the control plane.

The crashpoint facility (utils/crashpoints.py) proves the pipelines survive
*total* failure — the process dies at a commit point. This module is its
partner for *partial* failure: the apiserver stays up but misbehaves — slow
responses, dropped connections, 429 throttles, 5xx storms, spurious 409
conflicts, and watch streams that tear, duplicate, reorder, or silently
drop events. ChaosTransport (kubeapi/chaos.py) consults these sites on
every request/stream event, and the fake apiserver's HTTP watch handler
consults ``watch.stall`` to model a server that stops sending bytes.

Design notes (mirroring crashpoints):

- Zero-cost when disarmed: one dict read on the hot path, no lock (the
  armed map is only mutated from tests/harnesses).
- Faults are *Exceptions or status codes*, never BaseException: unlike a
  crash, a fault is exactly what the retry envelope and reconnect loops are
  built to absorb, so it must travel the recovery paths.
- Deterministic storms: rates are rolled on a module RNG reseeded via
  ``seed(n)`` so a chaos run replays bit-identically.
- ``rate=1.0`` + ``count=1`` gives the deterministic single-shot arming the
  unit tests use; the smoke arms fractional rates across every site.

Site inventory (asserted against the instrumented literals by
tests/test_chaos.py, the crashpoint-inventory-lint analogue — a new kube
call site must either reuse these sites or extend BOTH this tuple and the
instrumentation):

- ``api.request.get|post|put|patch|delete``  one per HTTP verb, crossed by
  every ChaosTransport.request (LIST is a collection GET)
- ``watch.open``    crossing a watch stream open (tear | gone faults)
- ``watch.event``   crossed per delivered watch event (latency | tear |
                    duplicate | reorder | drop-410)
- ``watch.stall``   consulted by the fake apiserver's HTTP watch handler:
                    hold events without closing the socket — the fault the
                    HttpTransport read-deadline exists to bound
- ``market.feed``   crossed by the market controller's feed poll
                    (controllers/market.py): ``stale`` holds back the
                    newest ticks (they redeliver), ``reorder`` scrambles
                    the batch (the seq-sorted fold absorbs it), and
                    ``blackout`` skips the poll — staleness climbs
- ``lease.cas``     crossed by the apiserver backend's lease CAS
                    (kubeapi/cluster.py acquire_lease): ``conflict`` loses
                    the CAS outright (a rival's update raced ours), while
                    ``commit-lost`` commits the server write but reports
                    the attempt lost — the classic split-brain seed, where
                    the holder must re-observe itself on the next campaign
- ``kubelet.register``   crossed by the fake-kubelet fleet
                    (tests/fake_kubelet.py) at node registration: ``drop``
                    = never-join (the Liveness guard's prey), ``delay`` =
                    slow-join (registration lands late but inside grace),
                    ``zombie`` = a DELETED node's kubelet re-registering
                    under its old name (the adoption-defense prey)
- ``kubelet.heartbeat``  crossed per heartbeat: ``drop`` = the kubelet goes
                    permanently dark mid-life (gone-dark detection prey),
                    ``flap`` = one beat reports NotReady then recovers
                    (the hysteresis must absorb it)
- ``kubelet.pod-ready``  crossed per pod-ready transition: ``delay`` holds
                    the transition back
- ``kubelet.eviction``   crossed per eviction the kubelet should complete:
                    ``black-hole`` = the pod sticks terminating forever
                    (the stuck-drain breaker's prey)
- ``solver.dispatch``    crossed per device solve batch (models/solver.py
                    CostSolver): ``oom`` raises RESOURCE_EXHAUSTED at the
                    dispatch/fetch choke point — the bisect-and-retry
                    ladder's prey (arm with count=N to force N split
                    depths before the batch fits)
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

SITES = (
    "api.request.get",
    "api.request.post",
    "api.request.put",
    "api.request.patch",
    "api.request.delete",
    "watch.open",
    "watch.event",
    "watch.stall",
    "market.feed",
    "lease.cas",
    "kubelet.register",
    "kubelet.heartbeat",
    "kubelet.pod-ready",
    "kubelet.eviction",
    "solver.dispatch",
)

REQUEST_SITES = tuple(s for s in SITES if s.startswith("api.request."))

# Which fault kinds make sense where — arm() rejects anything else so a
# typo'd kind fails the arming test, not silently never-fires.
KINDS_BY_SITE = {
    **{
        site: ("latency", "timeout", "reset", "throttle", "server-error", "conflict")
        for site in REQUEST_SITES
    },
    "watch.open": ("tear", "gone"),
    "watch.event": ("latency", "tear", "duplicate", "reorder", "drop-410"),
    "watch.stall": ("stall",),
    "market.feed": ("stale", "reorder", "blackout"),
    "lease.cas": ("conflict", "commit-lost"),
    "kubelet.register": ("drop", "delay", "zombie"),
    "kubelet.heartbeat": ("drop", "flap"),
    "kubelet.pod-ready": ("delay",),
    "kubelet.eviction": ("black-hole",),
    "solver.dispatch": ("oom",),
}


@dataclass
class Fault:
    """One armed fault: kind + rate + kind-specific parameters."""

    site: str
    kind: str
    rate: float = 1.0  # probability per passage
    count: Optional[int] = None  # max fires; None = unlimited
    delay_s: float = 0.0  # latency / stall duration
    retry_after_s: float = 1.0  # throttle: Status details.retryAfterSeconds
    status: int = 503  # server-error status code
    fires: int = 0  # times this fault actually fired


_lock = threading.Lock()
_armed: Dict[str, List[Fault]] = {}
_fired: Dict[str, int] = {}
_rng = random.Random(0)


def seed(value: int) -> None:
    """Reseed the roll RNG — a storm armed after seed(n) replays exactly."""
    with _lock:
        _rng.seed(value)


def arm(
    site: str,
    kind: str,
    rate: float = 1.0,
    count: Optional[int] = None,
    delay_s: float = 0.0,
    retry_after_s: float = 1.0,
    status: int = 503,
) -> Fault:
    """Arm `kind` at `site`; multiple faults may stack on one site (each is
    rolled independently, first winner fires). Returns the Fault so tests
    can read back .fires."""
    allowed = KINDS_BY_SITE.get(site)
    if allowed is None:
        raise ValueError(f"unknown fault site {site!r} (see faultpoints.SITES)")
    if kind not in allowed:
        raise ValueError(f"fault kind {kind!r} invalid at {site!r}; one of {allowed}")
    if not 0.0 < rate <= 1.0:
        raise ValueError(f"rate must be in (0, 1], got {rate}")
    fault = Fault(
        site=site, kind=kind, rate=rate, count=count,
        delay_s=delay_s, retry_after_s=retry_after_s, status=status,
    )
    with _lock:
        _armed.setdefault(site, []).append(fault)
    return fault


def draw(site: str) -> Optional[Fault]:
    """The injection call: returns the fault to apply at this passage of
    `site`, or None. No-op (one dict read, no lock) unless armed."""
    if not _armed:
        return None
    winner = None
    with _lock:
        faults = _armed.get(site)
        if not faults:
            return None
        for fault in faults:
            if fault.count is not None and fault.fires >= fault.count:
                continue
            if fault.rate < 1.0 and _rng.random() >= fault.rate:
                continue
            fault.fires += 1
            _fired[site] = _fired.get(site, 0) + 1
            winner = fault
            break
    if winner is not None:
        # Chaos is only diagnosable if the black box saw it: every injected
        # fault lands in the flight recorder (outside the site lock), so a
        # storm postmortem can line faults up against retries and launches.
        from karpenter_tpu.utils.obs import RECORDER

        RECORDER.record("fault", site=site, fault=winner.kind)
    return winner


def fires(site: str) -> bool:
    """Boolean convenience for sites whose fault carries no parameters
    (the fake apiserver's ``watch.stall`` handler)."""
    return draw(site) is not None


def fired(site: str) -> int:
    """How many faults have fired at `site` since the last disarm_all()."""
    with _lock:
        return _fired.get(site, 0)


def total_fired() -> int:
    with _lock:
        return sum(_fired.values())


def disarm_all() -> None:
    with _lock:
        _armed.clear()
        _fired.clear()


def any_armed() -> bool:
    return bool(_armed)
