"""Named crash-injection sites for crash-consistency testing.

The launch→register→bind pipeline buys capacity at one boundary and records
it at another; a controller that dies between the two must converge after a
restart without leaking instances or double-binding pods. That property is
only trustworthy if it is *executed*, so the pipeline threads named
`crashpoint(...)` sites through its commit points and the crash battletest
(tests/test_crash_consistency.py, `make crash-smoke`) arms each one in turn,
"kills" the controller there, restarts it, and asserts convergence.

Design notes:

- `SimulatedCrash` subclasses BaseException, NOT Exception. The pipeline is
  full of deliberate `except Exception` recovery (launch errors become
  per-node error lists, reconcile loops log-and-requeue); a *crash* must
  punch through all of it exactly like `os._exit` would, and be caught only
  by the test harness playing the role of the supervisor.
- Sites are zero-cost when disarmed: one dict read, no lock on the hot path
  (the armed map is only mutated from tests).
- `action="exit"` hard-kills the process (for subprocess-based harnesses);
  the default `action="raise"` stays in-process so a test can catch the
  crash and "restart" by building fresh controllers over the surviving
  store — the same state a real restart would observe.
- `at=N` fires on the Nth passage through the site (1-based), so e.g.
  `mid-bind` can let the first pod bind and kill the controller before the
  second.

Site inventory (see docs/design/crash-consistency.md):

- ``provision.before-launch``    batch drained, nothing bought yet
- ``cloud.after-create-fleet``   capacity bought, no callback/node yet
- ``provision.before-register``  node object about to be created
- ``provision.mid-bind``         fires per pod bind (arm with at=N)
- ``provision.after-bind``       node registered + pods bound, stats pending
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List

# The canonical site names, asserted by the lint in the crash battletest so
# the matrix can't silently drift from the instrumented code. SITES is the
# provisioning pipeline's matrix (tests/test_crash_consistency.py drives a
# provision pass into each); INTERRUPTION_SITES is the interruption
# pipeline's (tests/test_interruption.py drives a reclaim into each). The
# inventory lint asserts over the union.
SITES = (
    "provision.before-launch",
    "cloud.after-create-fleet",
    "provision.before-register",
    "provision.mid-bind",
    "provision.after-bind",
)

# Interruption pipeline commit points (docs/design/interruption.md):
# - ``interruption.after-annotate``  intent on the Node, event not yet acked
# - ``interruption.mid-drain``       fires per displaced pod (arm with at=N)
# - ``interruption.before-delete``   drain done, node deletion not yet issued
INTERRUPTION_SITES = (
    "interruption.after-annotate",
    "interruption.mid-drain",
    "interruption.before-delete",
)

# Consolidation pipeline commit points (docs/design/consolidation.md):
# - ``consolidation.after-nominate``  action annotation stamped on the
#   victim, nothing displaced yet
# - ``consolidation.mid-drain``       fires per displaced pod (arm with at=N)
# - ``consolidation.before-delete``   drain done, node deletion not yet issued
CONSOLIDATION_SITES = (
    "consolidation.after-nominate",
    "consolidation.mid-drain",
    "consolidation.before-delete",
)

# Market-fold commit point (docs/design/market.md):
# - ``market.mid-tick``  fires between folded market ticks (arm with at=N)
#   — a kill mid-fold leaves the PriceBook partially folded; the restart
#   re-polls the replayable feed from seq 0 and must reconstruct the
#   IDENTICAL book state and generation (the fold is a pure idempotent
#   function of the tick sequence; tests/test_market_feed.py asserts it on
#   both store backends).
MARKET_SITES = ("market.mid-tick",)

# Incremental-encode commit point (docs/design/incremental-encode.md):
# - ``encode.mid-apply``  fires inside DeviceClusterState's two-phase pod
#   sync, after the old contribution was removed and before the new one is
#   added — a kill here leaves the host bookkeeping torn, which the state
#   must detect (torn marker) and heal by rebuilding from the snapshot
#   path; the battletest asserts the rebuilt tensors are bit-identical to a
#   fresh snapshot encode.
ENCODE_SITES = ("encode.mid-apply",)

# Leader-election commit points (docs/operations.md, HA runbook):
# - ``leader.after-acquire``  the lease CAS committed and the fence armed,
#   but the Manager has not activated yet — a kill here leaves a held lease
#   that the standby can only take over after the TTL expires.
# - ``leader.before-renew``   fires at the top of each renewal attempt — a
#   kill here models the classic "died holding the lease mid-term" case.
LEADER_SITES = (
    "leader.after-acquire",
    "leader.before-renew",
)

# Unhealthy-node escalation commit points (docs/design/node-lifecycle.md):
# - ``health.after-cordon``   staleness confirmed and the victim cordoned,
#   nothing displaced yet — a restart must re-detect the same node (the
#   hysteresis counters are in-memory) and resume the ladder idempotently.
# - ``health.mid-displace``   fires per displaced pod (arm with at=N) — a
#   kill here leaves some pods rebound-pending and some still on the dying
#   node; the restart must finish the drain without double-displacing.
HEALTH_SITES = (
    "health.after-cordon",
    "health.mid-displace",
)

# Drift rolling-replacement commit points (docs/design/drift.md):
# - ``drift.after-mark``     drift kind annotation stamped on the victim,
#   nothing displaced yet — a restart resumes the replacement from the
#   durable annotation without re-detecting.
# - ``drift.mid-replace``    fires per displaced pod (arm with at=N) — a
#   kill here leaves some pods rebound-pending and some still on the
#   drifted node; the restart must finish without double-displacing.
# - ``drift.before-delete``  drain done, node deletion not yet issued.
DRIFT_SITES = (
    "drift.after-mark",
    "drift.mid-replace",
    "drift.before-delete",
)


class SimulatedCrash(BaseException):
    """The controller process 'died' at a named site. BaseException so no
    recovery path in the pipeline can swallow it (see module docstring)."""

    def __init__(self, site: str):
        super().__init__(f"simulated crash at {site}")
        self.site = site


@dataclass
class _Arm:
    action: str = "raise"  # "raise" | "exit"
    at: int = 1  # fire on the Nth passage (1-based)
    hits: int = 0  # passages so far while armed


_lock = threading.Lock()
_armed: Dict[str, _Arm] = {}
_passages: Dict[str, int] = {}  # every passage ever, armed or not
# Fired just before a crash executes (black-box hooks: the flight recorder
# registers a dump here so even an action="exit" kill — which skips atexit —
# leaves a forensic record). Append-only from module init; never under _lock.
_crash_callbacks: List = []
# Optional gate consulted on EVERY passage (armed or not). utils.fence
# installs one that aborts a deposed leader's sweep at the next site — the
# crashpoint inventory doubles as the set of cooperative-abort sites, so a
# long sweep straddling a leadership loss dies at its next commit point
# instead of draining to completion against the successor. Written once at
# module init (fence import); read lock-free like the armed map.
_abort_gate = None


def set_abort_gate(gate) -> None:
    """Install ``gate(site)`` to run at every crashpoint passage. The gate
    may raise to abort the sweep (utils.fence raises FencedWriteError)."""
    global _abort_gate
    _abort_gate = gate


def on_crash(callback) -> None:
    """Register callback(site) to run right before an armed crash fires."""
    _crash_callbacks.append(callback)


def crashpoint(name: str) -> None:
    """A named injection site. No-op unless a test armed `name`."""
    gate = _abort_gate
    if gate is not None:
        gate(name)
    # Lock-free fast path: dict reads are GIL-atomic and the armed map is
    # only written from tests, so production passes cost one lookup.
    if not _armed:
        if _passages:
            _count_passage(name)
        return
    _count_passage(name)
    with _lock:
        arm = _armed.get(name)
        if arm is None:
            return
        arm.hits += 1
        if arm.hits < arm.at:
            return
        del _armed[name]  # one-shot: the process only dies once
    for callback in _crash_callbacks:
        try:
            callback(name)
        except Exception:  # noqa: BLE001 — a black-box hook must not mask the crash
            pass
    if arm.action == "exit":
        os._exit(86)
    raise SimulatedCrash(name)


def _count_passage(name: str) -> None:
    with _lock:
        _passages[name] = _passages.get(name, 0) + 1


def arm(name: str, action: str = "raise", at: int = 1) -> None:
    """Arm `name` to fire on its `at`-th passage. One-shot."""
    if action not in ("raise", "exit"):
        raise ValueError(f"unknown crash action {action!r}")
    with _lock:
        _armed[name] = _Arm(action=action, at=at)
        _passages.setdefault(name, 0)


def disarm_all() -> None:
    with _lock:
        _armed.clear()
        _passages.clear()


def passages(name: str) -> int:
    """How many times `name` was crossed since passage counting started
    (counting starts at the first arm() and stops at disarm_all())."""
    with _lock:
        return _passages.get(name, 0)


def armed() -> List[str]:
    with _lock:
        return sorted(_armed)


def any_armed() -> bool:
    """Lock-free (same GIL-atomicity argument as the crashpoint fast path):
    lets instrumented code pick a deterministic serial path while a crash
    test is armed — e.g. bind fan-out, where a kill mid-fan-out would leave
    whichever sibling binds the pool happened to finish, not a reproducible
    minimal state."""
    return bool(_armed)
