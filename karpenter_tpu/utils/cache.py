"""Clock-injected TTL cache.

Ref: the reference leans on github.com/patrickmn/go-cache throughout the AWS
provider (aws/instancetypes.go:55-56, launchtemplate.go:61, subnets.go:25).
Ours takes a Clock so TTL expiry is deterministic under test (FakeClock)
instead of depending on wall time.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Hashable, Optional, Tuple

from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK

_MISSING = object()


class TtlCache:
    # Expired entries are only reaped when their key is looked up, so a
    # churn-heavy keyspace (e.g. pod UIDs) would otherwise grow without
    # bound; every SWEEP_INTERVAL-th set() purges all expired entries
    # (go-cache runs a janitor goroutine for the same reason).
    SWEEP_INTERVAL = 256

    def __init__(self, ttl: float, clock: Optional[Clock] = None):
        self.ttl = ttl
        self.clock = clock or SYSTEM_CLOCK
        self._entries: Dict[Hashable, Tuple[float, Any]] = {}  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()
        self._sets_since_sweep = 0  # vet: guarded-by(self._lock)

    def get(self, key: Hashable, default: Any = None) -> Any:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return default
            expires_at, value = entry
            if self.clock.now() >= expires_at:
                del self._entries[key]
                return default
            return value

    def __contains__(self, key: Hashable) -> bool:
        return self.get(key, _MISSING) is not _MISSING

    def set(self, key: Hashable, value: Any = None) -> None:
        """Store (or refresh the TTL of) key. The reference notes the same
        refresh-on-set semantics for ICE blackouts (instancetypes.go:181)."""
        with self._lock:
            now = self.clock.now()
            self._entries[key] = (now + self.ttl, value)
            self._sets_since_sweep += 1
            if self._sets_since_sweep >= self.SWEEP_INTERVAL:
                self._sets_since_sweep = 0
                for stale in [k for k, (exp, _) in self._entries.items() if exp <= now]:
                    del self._entries[stale]

    def delete(self, key: Hashable) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def keys(self):
        now = self.clock.now()
        with self._lock:
            return [k for k, (exp, _) in self._entries.items() if exp > now]
