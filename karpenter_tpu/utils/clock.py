"""Injectable clock (ref: pkg/utils/injectabletime/time.go — the reference
swaps a package-level Now var; we pass a Clock object so tests control time
without globals)."""

from __future__ import annotations

import threading
import time as _time


class Clock:
    def now(self) -> float:
        return _time.time()

    def monotonic(self) -> float:
        """Monotonic timestamps for durations/deadlines (rate limiters,
        tombstone TTLs) — never compare these against now()."""
        return _time.monotonic()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)


# Shared default for injectable-clock call sites (a Clock is stateless, so
# one instance serves every "no clock supplied" default). This module is the
# only one allowed to touch the raw time functions — tools/vet's
# clock-discipline checker holds every other production module to it.
SYSTEM_CLOCK = Clock()


class FakeClock(Clock):
    """Deterministic clock for TTL/expiry tests. One advancing timeline
    backs both now() and monotonic(), so wall-TTL and deadline logic move
    together under advance()."""

    def __init__(self, start: float = 1_000_000.0):
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def monotonic(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)

    def advance(self, seconds: float) -> None:
        with self._lock:
            self._now += seconds

    def set(self, timestamp: float) -> None:
        with self._lock:
            self._now = timestamp
