"""Injectable clock (ref: pkg/utils/injectabletime/time.go — the reference
swaps a package-level Now var; we pass a Clock object so tests control time
without globals)."""

from __future__ import annotations

import threading
import time as _time


class Clock:
    def now(self) -> float:
        return _time.time()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)


class FakeClock(Clock):
    """Deterministic clock for TTL/expiry tests."""

    def __init__(self, start: float = 1_000_000.0):
        self._now = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._now

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)

    def advance(self, seconds: float) -> None:
        with self._lock:
            self._now += seconds

    def set(self, timestamp: float) -> None:
        with self._lock:
            self._now = timestamp
