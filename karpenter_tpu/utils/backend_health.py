"""BackendHealth: the single device-liveness verdict for the whole process.

Round 5's own exam failed because liveness handling was scattered — the
probe, the CPU-pin, the solver dispatch gate, and the bench fallback each
made their own ad-hoc call, and `__graft_entry__` trusted JAX_PLATFORMS=cpu
and skipped the in-process pin entirely (hanging in backend init, rc:124).
This module owns that decision for everyone, the way the reference funnels
every exhausted-pool decision through one ICE blackout cache
(ref: aws/instancetypes.go:37,174-187):

    UNKNOWN --> PROBING --> HEALTHY
                        \\-> DEGRADED(reason)

- The probe runs in a SUBPROCESS with a hard timeout (a wedged tunnel hangs
  jax inside C, uninterruptible from Python, so the probe must be killable
  from outside). Its stderr — which names the actual cause: import error,
  libtpu, backend init — is captured and forwarded on failure AND on
  timeout (partial output), and the outcome + duration are exported as the
  `backend_probe_result` / `backend_probe_duration_seconds` gauges.
- The verdict is cached with a TTL: a DEGRADED verdict older than
  VERDICT_TTL_SECONDS re-probes (in the background from the routing
  predicate, synchronously from verdict()) so a recovered tunnel is picked
  back up without a restart.
- `pin_cpu()` is the one CPU-backend pin. Under the axon TPU harness a
  sitecustomize registers the 'axon' PJRT backend at interpreter start —
  before env vars can steer backend choice — so the pin ALWAYS pops the
  axon factory, including when JAX_PLATFORMS=cpu is already set (trusting
  the env alone is exactly the r05 hang). It pokes a private jax attribute,
  so it lives in exactly one place.

Consumers: `__graft_entry__.entry()`, `bench.py`, `runtime.Manager` boot,
the solver sidecar's `main()` (all via `ensure_backend()`), and the solve
dispatch gate (`models/solver.host_solve_enabled` via `degraded()`).
`dryrun_multichip` pins the virtual CPU mesh unconditionally via
`pin_cpu(host_devices=...)` — no probe, no env guard.

Fault injection (extends the injectable-probe pattern of the liveness
tests): BackendHealth takes a probe callable and a Clock, so every state
transition is unit-testable without a real device; at the process level,
KARPENTER_PROBE_CODE / KARPENTER_PROBE_TIMEOUT_S override what the
subprocess probe runs (the `make degraded-smoke` wedge).

This module must stay jax-import-free at module level and is the ONLY
module allowed to read JAX_PLATFORMS or touch devices at import time —
enforced by tests/test_backend_lint.py.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils.clock import Clock, SYSTEM_CLOCK
from karpenter_tpu.utils.metrics import REGISTRY

log = klog.named("backend-health")

# Machine states. UNKNOWN/HEALTHY/DEGRADED are settled verdicts routing can
# act on; PROBING is transient (routing keeps the last settled verdict).
UNKNOWN = "unknown"
PROBING = "probing"
HEALTHY = "healthy"
DEGRADED = "degraded"

# Hard probe budget: a healthy probe answers in ~1-2s (a python + jax import
# and one 8-element fetch); 30s is generous for a cold tunnel yet keeps every
# entry point's worst case far inside the driver's 60s artifact budget (the
# old 120s default consumed two thirds of it before doing any work).
PROBE_TIMEOUT_SECONDS = 30.0
# Verdict TTL: how long a verdict stands before a re-probe. Long enough that
# the solve path never waits on probes, short enough that a recovered tunnel
# is picked back up within minutes (the re-probe from the routing predicate
# is backgrounded, so recovery costs no solve any latency).
VERDICT_TTL_SECONDS = 300.0

# Exactly what a first in-process device touch would do, in a killable child.
_PROBE_CODE = (
    "import jax, jax.numpy as jnp; jax.device_get(jnp.ones((8,)) + 1)"
)

PROBE_RESULT = REGISTRY.gauge(
    "backend_probe_result",
    "Last device-liveness probe outcome (1 healthy, 0 degraded) — alert on 0",
)
PROBE_DURATION = REGISTRY.gauge(
    "backend_probe_duration_seconds",
    "Wall time of the last device-liveness probe",
)


@dataclass(frozen=True)
class ProbeResult:
    """One probe attempt: ok, how long it took, and — when it failed — why
    (reason) plus whatever the child managed to write to stderr."""

    ok: bool
    duration_s: float
    reason: str = ""
    stderr: str = ""


@dataclass(frozen=True)
class Verdict:
    """A settled liveness verdict (never PROBING)."""

    state: str
    reason: str
    probed_at: Optional[float]
    duration_s: float


def run_subprocess_probe(
    timeout_s: float, probe_code: Optional[str] = None
) -> ProbeResult:
    """The hardened probe: run a first-device-touch in a subprocess with a
    hard timeout. stderr is captured in BOTH outcomes — a failing child's
    full stderr, and a hung child's PARTIAL stderr (everything it wrote
    before the kill), which is often the only clue naming where backend
    init wedged. KARPENTER_PROBE_CODE overrides the child program (the
    fault-injection seam for `make degraded-smoke`)."""
    import subprocess
    import sys
    import time as _time

    code = probe_code or os.environ.get("KARPENTER_PROBE_CODE") or _PROBE_CODE
    # The probe's question is "is the ACCELERATOR alive" — but after a
    # DEGRADED verdict pin_cpu() writes JAX_PLATFORMS=cpu into os.environ,
    # and a child inheriting it would probe the CPU backend, trivially pass,
    # and flip the verdict to a false HEALTHY on the next TTL re-probe.
    # Strip it so the child always faces the accelerator.
    child_env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    start = _time.perf_counter()
    try:
        probe = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            env=child_env,
        )
        duration = _time.perf_counter() - start
        stderr = probe.stderr.decode(errors="replace") if probe.stderr else ""
        if probe.returncode != 0:
            return ProbeResult(
                False, duration, f"probe exited {probe.returncode}", stderr
            )
        return ProbeResult(True, duration, "", stderr)
    except subprocess.TimeoutExpired as exc:
        duration = _time.perf_counter() - start
        partial = exc.stderr
        if isinstance(partial, bytes):
            partial = partial.decode(errors="replace")
        return ProbeResult(
            False,
            duration,
            f"probe hung past {timeout_s:g}s (wedged tunnel?)",
            partial or "",
        )


def _forward_stderr(result: ProbeResult) -> None:
    """Surface a failed probe's cause on THIS process's stderr — on timeout
    as well as on failure (the r05 gap: a hung probe reported nothing)."""
    import sys

    message = f"device probe degraded: {result.reason}\n"
    if result.stderr:
        message += result.stderr.rstrip("\n") + "\n"
    sys.stderr.write(message)


class BackendHealth:
    """The state machine. One instance (module-level BACKEND) serves the
    process; tests build their own with an injected probe + FakeClock."""

    def __init__(
        self,
        probe: Optional[Callable[[float], ProbeResult]] = None,
        clock: Optional[Clock] = None,
        timeout_s: float = PROBE_TIMEOUT_SECONDS,
        ttl_s: float = VERDICT_TTL_SECONDS,
    ):
        self._probe = probe or run_subprocess_probe
        self._clock = clock or SYSTEM_CLOCK
        self.timeout_s = timeout_s
        self.ttl_s = ttl_s
        self._lock = threading.RLock()
        self._state = UNKNOWN  # vet: guarded-by(self._lock) — machine state, may be PROBING
        self._settled = UNKNOWN  # vet: guarded-by(self._lock) — last settled verdict, what routing reads
        self._reason = ""  # vet: guarded-by(self._lock)
        self._probed_at: Optional[float] = None  # vet: guarded-by(self._lock)
        self._duration_s = 0.0  # vet: guarded-by(self._lock)
        self._reprobe_thread: Optional[threading.Thread] = None
        # (from, to) log — the unit tests assert exact transition sequences.
        self.transitions: List[Tuple[str, str]] = []

    # --- state ----------------------------------------------------------

    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> Verdict:
        with self._lock:
            return Verdict(
                self._settled, self._reason, self._probed_at, self._duration_s
            )

    def healthy(self) -> bool:
        with self._lock:
            return self._settled == HEALTHY

    def degraded(self) -> bool:
        """THE routing predicate — cheap and non-blocking, safe on the solve
        path: True while the last settled verdict is DEGRADED. An expired
        DEGRADED verdict kicks a background re-probe (a recovered tunnel is
        picked back up) while routing keeps the stale verdict until the new
        one lands — degraded service beats a solve blocked behind a probe."""
        with self._lock:
            if (
                self._settled == DEGRADED
                and self._state != PROBING
                and self._expired(self._clock.now())
            ):
                self._transition(PROBING)
                self._reprobe_thread = threading.Thread(
                    target=lambda: self._record(self._run_probe()),
                    name="backend-reprobe",
                    daemon=True,
                )
                self._reprobe_thread.start()
            return self._settled == DEGRADED

    def verdict(self, force: bool = False) -> Verdict:
        """The single device-liveness verdict: probes (blocking) when none
        exists yet, the cached one outlived its TTL, or force=True;
        otherwise answers from the cache."""
        with self._lock:
            need = (
                force
                or self._settled == UNKNOWN
                or self._expired(self._clock.now())
            )
            if not need or self._state == PROBING:
                # A probe already in flight: answer with the last settled
                # verdict rather than queueing behind the subprocess.
                return self.snapshot()
            self._transition(PROBING)
        self._record(self._run_probe())
        return self.snapshot()

    def reset(self) -> None:
        """Test hook: return to UNKNOWN with an empty transition log."""
        with self._lock:
            self._state = UNKNOWN
            self._settled = UNKNOWN
            self._reason = ""
            self._probed_at = None
            self._duration_s = 0.0
            self.transitions = []

    def _expired(self, now: float) -> bool:  # vet: holds(self._lock)
        return self._probed_at is None or (now - self._probed_at) > self.ttl_s

    def _transition(self, to: str, reason: str = "") -> None:  # vet: holds(self._lock)
        """Record a state change (caller holds the lock). Settled states
        also update the routing verdict and its reason."""
        if to != self._state:
            self.transitions.append((self._state, to))
            self._state = to
        if to in (UNKNOWN, HEALTHY, DEGRADED):
            self._settled = to
            self._reason = reason

    def _run_probe(self) -> ProbeResult:
        # Everything — the env parse included — maps to DEGRADED rather than
        # raising: an exception escaping here would strand the machine in
        # PROBING forever (no later call could ever re-probe).
        try:
            timeout = float(
                os.environ.get("KARPENTER_PROBE_TIMEOUT_S", self.timeout_s)
            )
            return self._probe(timeout)
        except Exception as error:  # noqa: BLE001 — a broken probe is a dead device
            return ProbeResult(False, 0.0, f"probe raised {error!r}")

    def _record(self, result: ProbeResult) -> None:
        if not result.ok:
            _forward_stderr(result)
        with self._lock:
            self._probed_at = self._clock.now()
            self._duration_s = result.duration_s
            self._transition(
                HEALTHY if result.ok else DEGRADED, result.reason
            )
        PROBE_RESULT.set(1.0 if result.ok else 0.0)
        PROBE_DURATION.set(result.duration_s)
        if result.ok:
            log.info("device probe healthy in %.2fs", result.duration_s)
        else:
            log.warning(
                "device probe DEGRADED after %.2fs: %s",
                result.duration_s,
                result.reason,
            )

    # --- backend control -------------------------------------------------

    def pin_cpu(self, host_devices: Optional[int] = None, reset: bool = False):
        """Pin jax to the CPU backend in-process; returns the jax module.
        Idempotent, and it ALWAYS pops the axon factory — including when
        JAX_PLATFORMS=cpu is already set in the env, because under the axon
        harness the sitecustomize registered the factory before the env
        could steer backend choice and selecting cpu via env alone hangs in
        backend init (the r05 rc:124).

        host_devices: also request an N-device virtual CPU mesh (replaces
        any prior count so repeated pins can't stack flags; must be set
        before the CPU backend initializes). reset: clear already-
        initialized backends first — needed when the caller already touched
        a device before deciding to switch."""
        if host_devices:
            flags = [
                flag
                for flag in os.environ.get("XLA_FLAGS", "").split()
                if not flag.startswith("--xla_force_host_platform_device_count=")
            ]
            flags.append(
                f"--xla_force_host_platform_device_count={host_devices}"
            )
            os.environ["XLA_FLAGS"] = " ".join(flags)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        try:
            import jax._src.xla_bridge as _xb

            _xb._backend_factories.pop("axon", None)
        except Exception:  # pragma: no cover — jax internals moved; env still set
            pass
        if reset:
            import jax.extend.backend

            jax.extend.backend.clear_backends()
        return jax

    def ensure_backend(self) -> Verdict:
        """Entry-point backend setup — the one discipline shared by
        entry(), bench, the Manager boot, and the sidecar:

        - env already says cpu: pin the CPU backend anyway (always pop the
          axon factory — the env alone cannot steer the harness) and settle
          a HEALTHY("cpu-pinned") verdict without probing: the configured
          backend IS the cpu, and it is alive by construction.
        - otherwise: take the verdict (cached, TTL re-probe) and on
          DEGRADED pin the CPU backend BEFORE any in-process device touch.
        """
        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            self.pin_cpu()
            with self._lock:
                self._probed_at = self._clock.now()
                self._duration_s = 0.0
                self._transition(HEALTHY, "cpu-pinned")
            PROBE_RESULT.set(1.0)
            PROBE_DURATION.set(0.0)
            return self.snapshot()
        settled = self.verdict()
        if settled.state == DEGRADED:
            self.pin_cpu()
        return settled


# --- per-chip (mesh) health ----------------------------------------------
#
# The verdict machine above answers "is THE accelerator alive" — one bit for
# the whole process, and DEGRADED means the CPU pin. A multi-chip mesh needs
# a finer verdict: "1 of N chips wedged" must shrink the mesh and re-lower
# the sharded kernel on the survivors (parallel/mesh.make_mesh excludes the
# quarantined chips; models/solver._dispatch_sharded retries once on the
# shrunk mesh), NOT collapse an 8-chip runtime onto the CPU. MeshHealth owns
# that chip set; it is deliberately separate state from the verdict machine
# so a wedged chip never flips the routing predicate host_solve_enabled
# consults (docs/design/sharded-solve.md).

WEDGED_CHIPS = REGISTRY.gauge(
    "backend_wedged_chips",
    "Chips quarantined out of the solver mesh — alert on > 0",
)

# Per-chip probe: touch every device in enumeration order, reporting each
# survivor on stdout BEFORE touching the next — a wedged chip hangs the
# child there, and the parent reads the partial output to learn exactly
# which chips answered. KARPENTER_CHIP_PROBE_CODE overrides the child (the
# fault-injection seam for tests and `make multichip-smoke`).
_CHIP_PROBE_CODE = (
    "import jax, jax.numpy as jnp\n"
    "for d in jax.devices():\n"
    "    jax.device_get(jax.device_put(jnp.ones((8,)), d) + 1)\n"
    "    print(f'CHIP_OK {d.id}', flush=True)\n"
)
_CHIP_OK_PREFIX = "CHIP_OK "


def _decode_stream(data) -> str:
    if isinstance(data, bytes):
        return data.decode(errors="replace")
    return data or ""


def _parse_chip_ok(stdout: str) -> List[int]:
    ok_ids = []
    for line in stdout.splitlines():
        suffix = line[len(_CHIP_OK_PREFIX) :]
        if line.startswith(_CHIP_OK_PREFIX) and suffix.isdigit():
            ok_ids.append(int(suffix))
    return ok_ids


def run_chip_probe(
    timeout_s: float, probe_code: Optional[str] = None
) -> Tuple[List[int], ProbeResult]:
    """Probe every chip in a killable subprocess. Returns (ok_ids, result):
    ok_ids are the chips that answered before the child finished or was
    killed; result carries the overall outcome exactly like the whole-device
    probe (partial stdout is parsed in BOTH outcomes — on a timeout the
    survivors printed before the hang are the diagnostic)."""
    import subprocess
    import sys
    import time as _time

    code = (
        probe_code
        or os.environ.get("KARPENTER_CHIP_PROBE_CODE")
        or _CHIP_PROBE_CODE
    )
    child_env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    start = _time.perf_counter()
    try:
        probe = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            env=child_env,
        )
        duration = _time.perf_counter() - start
        ok = probe.returncode == 0
        reason = "" if ok else f"chip probe exited {probe.returncode}"
        stdout = _decode_stream(probe.stdout)
        result = ProbeResult(ok, duration, reason, _decode_stream(probe.stderr))
    except subprocess.TimeoutExpired as exc:
        duration = _time.perf_counter() - start
        stdout = _decode_stream(exc.stdout)
        result = ProbeResult(
            False,
            duration,
            f"chip probe hung past {timeout_s:g}s (wedged chip?)",
            _decode_stream(exc.stderr),
        )
    return _parse_chip_ok(stdout), result


class MeshHealth:
    """The quarantined-chip set. Chips enter via report_chip_wedged (a
    failed sharded dispatch's quarantine probe, an operator action, a test)
    and leave via clear() or a full-mesh re-probe that sees them answer."""

    def __init__(self, clock: Optional[Clock] = None):
        self._clock = clock or SYSTEM_CLOCK
        self._lock = threading.Lock()
        self._wedged: dict = {}  # vet: guarded-by(self._lock) — chip id -> reason
        self._reported_at: dict = {}  # vet: guarded-by(self._lock) — chip id -> clock time

    def report_chip_wedged(self, device_id: int, reason: str) -> None:
        fresh = False
        with self._lock:
            if device_id not in self._wedged:
                fresh = True
                log.warning(
                    "chip %d quarantined out of the solver mesh: %s",
                    device_id,
                    reason,
                )
            self._wedged[device_id] = reason
            self._reported_at[device_id] = self._clock.now()
            WEDGED_CHIPS.set(float(len(self._wedged)))
        if fresh:
            # Quarantines are exactly the class of rare, consequential event
            # the flight recorder exists for (recorded outside the lock).
            from karpenter_tpu.utils.obs import RECORDER

            RECORDER.record("quarantine", chip=device_id, reason=reason)

    def clear(self, device_id: Optional[int] = None) -> None:
        """Un-quarantine one chip (a re-probe saw it answer) or, with no
        argument, the whole set (test hook / operator reset)."""
        with self._lock:
            if device_id is None:
                self._wedged.clear()
                self._reported_at.clear()
            else:
                self._wedged.pop(device_id, None)
                self._reported_at.pop(device_id, None)
            WEDGED_CHIPS.set(float(len(self._wedged)))

    def wedged(self) -> dict:
        with self._lock:
            return dict(self._wedged)

    def degraded(self) -> bool:
        with self._lock:
            return bool(self._wedged)

    def quarantine(
        self,
        device_ids: List[int],
        error: object,
        timeout_s: float = PROBE_TIMEOUT_SECONDS,
    ) -> List[int]:
        """A sharded dispatch over `device_ids` failed with `error`: probe
        every chip in a killable child and quarantine the non-responders.
        Returns the NEWLY wedged ids ([] when every chip answered — the
        failure was not a dead chip, and the caller should re-raise)."""
        ok_ids, result = run_chip_probe(
            float(os.environ.get("KARPENTER_PROBE_TIMEOUT_S", timeout_s))
        )
        if result.ok and set(device_ids) <= set(ok_ids):
            return []
        newly = [d for d in device_ids if d not in ok_ids]
        for device_id in newly:
            self.report_chip_wedged(
                device_id,
                f"no answer to quarantine probe after dispatch error: {error}"
                + (f" ({result.reason})" if result.reason else ""),
            )
        return newly


MESH = MeshHealth()


def wedged_chips() -> dict:
    return MESH.wedged()


def mesh_degraded() -> bool:
    """True while at least one chip is quarantined — the first-class
    "1 of N chips wedged" state: the mesh shrinks, solves stay on device."""
    return MESH.degraded()


def report_chip_wedged(device_id: int, reason: str) -> None:
    MESH.report_chip_wedged(device_id, reason)


def clear_wedged_chips() -> None:
    MESH.clear()


def quarantine_mesh(device_ids: List[int], error: object) -> List[int]:
    return MESH.quarantine(device_ids, error)


# The process-wide instance every production consumer shares.
BACKEND = BackendHealth()


def state() -> str:
    return BACKEND.state()


def verdict(force: bool = False) -> Verdict:
    return BACKEND.verdict(force=force)


def degraded() -> bool:
    return BACKEND.degraded()


def ensure_backend() -> Verdict:
    return BACKEND.ensure_backend()


def pin_cpu(host_devices: Optional[int] = None, reset: bool = False):
    return BACKEND.pin_cpu(host_devices=host_devices, reset=reset)


def reset() -> None:
    BACKEND.reset()


# --- compatibility: utils/jaxenv absorbed here ---------------------------


def force_cpu_backend(host_devices: Optional[int] = None, reset: bool = False):
    """Legacy name for pin_cpu (utils/jaxenv re-exports it)."""
    return BACKEND.pin_cpu(host_devices=host_devices, reset=reset)


def device_alive(
    timeout_s: float = PROBE_TIMEOUT_SECONDS, _probe_code: str = _PROBE_CODE
) -> bool:
    """One-shot probe (legacy utils/jaxenv API): same hardened subprocess
    probe, stderr forwarded on failure and timeout, but does NOT update the
    process verdict — new code should use verdict()/ensure_backend()."""
    result = run_subprocess_probe(timeout_s, probe_code=_probe_code)
    if not result.ok:
        _forward_stderr(result)
    return result.ok
