"""Webhook serving-certificate self-provisioning and rotation.

Ref: cmd/webhook/main.go:44-62 — the reference's knative sharedmain runs a
certificate controller that generates the webhook's serving cert, rotates it
before expiry, and injects the CA bundle into the webhook configurations.
This module is that controller re-built for this runtime: generate a
self-signed serving cert when the operator supplies none, serve it from an
SSLContext that hot-reloads on rotation (no listener restart), and write the
caBundle into the Mutating/ValidatingWebhookConfiguration objects through
the apiserver client.
"""

from __future__ import annotations

import base64
import datetime
import os
import tempfile
import threading
from typing import Callable, List, Optional, Sequence, Tuple

from karpenter_tpu.utils import logging as klog

log = klog.named("webhook.certs")

# Rotate when less than this fraction of the cert lifetime remains (knative
# rotates at 90% of lifetime; 20% remaining ≈ the same renewal cadence with
# margin for a webhook that only checks hourly).
ROTATE_REMAINING_FRACTION = 0.2

MUTATING_WEBHOOK_NAME = "defaulting.webhook.karpenter.tpu"
VALIDATING_WEBHOOK_NAME = "validation.webhook.karpenter.tpu"


def generate_self_signed(
    common_name: str,
    dns_names: Sequence[str] = (),
    lifetime: datetime.timedelta = datetime.timedelta(days=90),
    now: Optional[datetime.datetime] = None,
) -> Tuple[bytes, bytes]:
    """(cert_pem, key_pem): a self-signed EC-P256 serving certificate with
    the given SANs. The cert doubles as its own CA bundle (self-signed),
    exactly like knative's generated secret."""
    import ipaddress

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    now = now or datetime.datetime.now(datetime.timezone.utc)
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    sans: List[x509.GeneralName] = []
    for dns in dns_names or (common_name,):
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(dns)))
        except ValueError:
            sans.append(x509.DNSName(dns))
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + lifetime)
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .add_extension(
            x509.BasicConstraints(ca=True, path_length=None), critical=True
        )
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    return cert_pem, key_pem


class CertManager:
    """Owns the webhook's serving cert files: generates when absent, rotates
    before expiry, hot-reloads any registered SSLContext, and notifies a
    callback with the fresh base64 caBundle (for re-injection)."""

    def __init__(
        self,
        common_name: str,
        dns_names: Sequence[str] = (),
        lifetime: datetime.timedelta = datetime.timedelta(days=90),
        cert_dir: Optional[str] = None,
        clock: Callable[[], datetime.datetime] = None,
    ):
        self.common_name = common_name
        self.dns_names = tuple(dns_names) or (common_name,)
        self.lifetime = lifetime
        self.cert_dir = cert_dir or tempfile.mkdtemp(prefix="karpenter-webhook-")
        self.cert_path = os.path.join(self.cert_dir, "tls.crt")
        self.key_path = os.path.join(self.cert_dir, "tls.key")
        self._clock = clock or (
            lambda: datetime.datetime.now(datetime.timezone.utc)
        )
        self._not_after: Optional[datetime.datetime] = None
        self._not_before: Optional[datetime.datetime] = None
        self._lock = threading.Lock()
        self._contexts: List = []  # SSLContexts to hot-reload on rotation
        self.on_rotate: Optional[Callable[[str], None]] = None
        self._stop = threading.Event()

    # --- provisioning -------------------------------------------------------

    def ensure(self) -> Tuple[str, str]:
        """Generate the serving cert if missing or due; returns file paths."""
        with self._lock:
            if self._not_after is None or self._due_locked():
                self._generate_locked()
            return self.cert_path, self.key_path

    def ca_bundle_b64(self) -> str:
        with open(self.cert_path, "rb") as handle:
            return base64.b64encode(handle.read()).decode()

    def _generate_locked(self) -> None:
        now = self._clock()
        cert_pem, key_pem = generate_self_signed(
            self.common_name, self.dns_names, self.lifetime, now=now
        )
        # Write key with owner-only permissions before the cert appears.
        descriptor = os.open(
            self.key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600
        )
        with os.fdopen(descriptor, "wb") as handle:
            handle.write(key_pem)
        with open(self.cert_path, "wb") as handle:
            handle.write(cert_pem)
        self._not_before = now
        self._not_after = now + self.lifetime
        log.info(
            "provisioned self-signed serving cert for %s (SAN %s), expires %s",
            self.common_name, ",".join(self.dns_names), self._not_after,
        )

    # --- rotation -----------------------------------------------------------

    def _due_locked(self) -> bool:
        if self._not_after is None:
            return True
        remaining = (self._not_after - self._clock()).total_seconds()
        return remaining < self.lifetime.total_seconds() * ROTATE_REMAINING_FRACTION

    def due_for_rotation(self) -> bool:
        with self._lock:
            return self._due_locked()

    def register_context(self, context) -> None:
        """SSLContexts registered here are re-loaded with the new chain on
        every rotation — new handshakes pick up the fresh cert, no listener
        restart."""
        with self._lock:
            self._contexts.append(context)

    def rotate_if_due(self) -> bool:
        with self._lock:
            if not self._due_locked():
                return False
            self._generate_locked()
            for context in self._contexts:
                context.load_cert_chain(self.cert_path, self.key_path)
        self._notify()
        return True

    def _notify(self) -> None:
        if self.on_rotate:
            try:
                self.on_rotate(self.ca_bundle_b64())
            except Exception:  # noqa: BLE001 — reconciled on the next tick
                log.exception("caBundle injection failed; will retry")

    def start_rotation_thread(self, interval_s: float = 60.0) -> threading.Thread:
        """Reconcile loop: rotate when due, and RE-INJECT the bundle every
        tick regardless (inject_ca_bundle no-ops when current). Injection
        must not wait for the next rotation: the chart's webhook
        configurations may be applied after the pod starts (Helm kind
        ordering), and a failed post-rotation injection would otherwise
        leave admission broken for the rest of the cert's lifetime."""

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    if self.rotate_if_due():
                        log.info("rotated webhook serving cert")
                    else:
                        self._notify()
                except Exception:  # noqa: BLE001 — keep the loop alive
                    log.exception("cert rotation check failed")

        thread = threading.Thread(target=loop, daemon=True, name="cert-rotation")
        thread.start()
        return thread

    def stop(self) -> None:
        self._stop.set()


def inject_ca_bundle(
    client,
    ca_bundle_b64: str,
    mutating: Sequence[str] = (MUTATING_WEBHOOK_NAME,),
    validating: Sequence[str] = (VALIDATING_WEBHOOK_NAME,),
) -> int:
    """Write the CA bundle into every webhook entry of the named
    webhook-configurations via the apiserver (read-modify-write — a merge
    patch would clobber sibling fields of the webhooks list). Returns the
    number of configurations updated; missing configurations are skipped
    (the chart may register them later). Ref: knative's certificate
    controller updating clientConfig.caBundle."""
    updated = 0
    plans = [
        ("/apis/admissionregistration.k8s.io/v1/mutatingwebhookconfigurations",
         mutating),
        ("/apis/admissionregistration.k8s.io/v1/validatingwebhookconfigurations",
         validating),
    ]
    for base_path, names in plans:
        for name in names:
            obj = client.try_get(f"{base_path}/{name}")
            if obj is None:
                log.info("webhook configuration %s not found; skipping", name)
                continue
            changed = False
            for webhook in obj.get("webhooks", []):
                config = webhook.setdefault("clientConfig", {})
                if config.get("caBundle") != ca_bundle_b64:
                    config["caBundle"] = ca_bundle_b64
                    changed = True
            if changed:
                client.update(f"{base_path}/{name}", obj)
                updated += 1
    return updated
