"""Minimal Prometheus-style metrics registry (no external dependency).

Ref: pkg/metrics/constants.go — namespace "karpenter", duration buckets
matching controller-runtime; gauges/histograms rendered in text exposition
format for scraping.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Sequence, Tuple

NAMESPACE = "karpenter"

# ref: metrics.DurationBuckets — 5ms..60s ramp used by the reference.
DURATION_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.15, 0.2, 0.25, 0.3, 0.35, 0.4, 0.45,
    0.5, 0.6, 0.7, 0.8, 0.9, 1.0, 1.25, 1.5, 1.75, 2.0, 2.5, 3.0, 4.0, 5.0,
    6.0, 7.0, 8.0, 9.0, 10.0, 15.0, 20.0, 25.0, 30.0, 40.0, 50.0, 60.0,
)

LabelValues = Tuple[str, ...]


def escape_label_value(value) -> str:
    """Text-exposition-format escaping for label VALUES: backslash, double
    quote, and newline must be escaped or the rendered line is malformed and
    the whole /metrics page fails to parse (Prometheus exposition spec §
    'Comments, help text, and type information'). Reason strings routinely
    carry quotes (exception reprs) — they flow in via sweep_failures_total."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def render_labels(names: Sequence[str], values: LabelValues) -> str:
    """'k1="v1",k2="v2"' with values escaped — the one label serializer both
    metric types render through, so escaping cannot drift between them."""
    return ",".join(
        f'{n}="{escape_label_value(v)}"' for n, v in zip(names, values)
    )


class _Timer:
    """Histogram.measure() context manager, hoisted to module level — the
    previous closure built a fresh class object per measured block, which at
    one measure per reconcile was real storm-path overhead."""

    __slots__ = ("_histogram", "_labels", "start")

    def __init__(self, histogram: "Histogram", labels: LabelValues):
        self._histogram = histogram
        self._labels = labels

    def __enter__(self):
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._histogram.observe(time.perf_counter() - self.start, *self._labels)
        return False


class Gauge:
    metric_type = "gauge"

    def __init__(self, name: str, help_text: str, labels: Sequence[str] = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(labels)
        self._values: Dict[LabelValues, float] = {}  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()

    def set(self, value: float, *label_values: str) -> None:
        with self._lock:
            self._values[tuple(label_values)] = value

    def inc(self, *label_values: str, amount: float = 1.0) -> None:
        with self._lock:
            key = tuple(label_values)
            self._values[key] = self._values.get(key, 0.0) + amount

    def get(self, *label_values: str) -> float:
        with self._lock:
            return self._values.get(tuple(label_values), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def remove_where(self, predicate) -> None:
        """Drop series whose label tuple matches — lets pollers clear stale
        series (a vanished zone must not keep reporting its last count)."""
        with self._lock:
            for key in [k for k in self._values if predicate(k)]:
                del self._values[key]

    def render(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} {self.metric_type}",
        ]
        with self._lock:
            for label_values, value in sorted(self._values.items()):
                if self.label_names:
                    labels = render_labels(self.label_names, label_values)
                    lines.append(f"{self.name}{{{labels}}} {value}")
                else:
                    # Label-free series (e.g. backend_probe_result) render
                    # without the empty brace pair.
                    lines.append(f"{self.name} {value}")
        return lines


class Counter(Gauge):
    """Monotonic counter: inc() only, rendered with the counter type so
    rate()/increase() work in PromQL."""

    metric_type = "counter"

    def set(self, value: float, *label_values: str) -> None:
        raise TypeError(f"{self.name} is a Counter; use inc(), not set()")


class Histogram:
    def __init__(
        self,
        name: str,
        help_text: str,
        labels: Sequence[str] = (),
        buckets: Sequence[float] = DURATION_BUCKETS,
    ):
        self.name = name
        self.help = help_text
        self.label_names = tuple(labels)
        self.buckets = tuple(buckets)
        self._counts: Dict[LabelValues, List[int]] = {}  # vet: guarded-by(self._lock)
        self._sums: Dict[LabelValues, float] = {}  # vet: guarded-by(self._lock)
        self._totals: Dict[LabelValues, int] = {}  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()

    def observe(self, value: float, *label_values: str) -> None:
        # Per-bin (non-cumulative) storage, one bisect per observe: every
        # reconcile crosses this under a process-wide lock, and an O(buckets)
        # loop here convoys the 8-way selection pool during a pod storm
        # (sampled as the single largest busy stack in bench_pod_storm).
        # render() restores Prometheus's cumulative view.
        key = tuple(label_values)
        index = bisect.bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
            counts[index] += 1  # index == len(buckets) → the +Inf bin
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def observe_many(self, values: Sequence[float], *label_values: str) -> None:
        """Record a batch of observations under ONE lock acquisition — the
        reconcile loops observe per-key durations chunk-at-a-time so a
        128-thread pool doesn't convoy on this lock (one acquire per chunk
        instead of per reconcile)."""
        if not values:
            return
        key = tuple(label_values)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.buckets) + 1)
            total = 0.0
            for value in values:
                counts[bisect.bisect_left(self.buckets, value)] += 1
                total += value
            self._sums[key] = self._sums.get(key, 0.0) + total
            self._totals[key] = self._totals.get(key, 0) + len(values)

    def measure(self, *label_values: str):
        """Context manager timing a block (ref: metrics.Measure defer-timer)."""
        return _Timer(self, label_values)

    def count(self, *label_values: str) -> int:
        with self._lock:
            return self._totals.get(tuple(label_values), 0)

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, counts in sorted(self._counts.items()):
                base = render_labels(self.label_names, key)
                sep = "," if base else ""
                running = 0
                for bound, count in zip(self.buckets, counts):
                    running += count
                    lines.append(
                        f'{self.name}_bucket{{{base}{sep}le="{bound}"}} {running}'
                    )
                lines.append(
                    f'{self.name}_bucket{{{base}{sep}le="+Inf"}} {self._totals[key]}'
                )
                lines.append(f"{self.name}_sum{{{base}}} {self._sums[key]}")
                lines.append(f"{self.name}_count{{{base}}} {self._totals[key]}")
        return lines


class Registry:
    def __init__(self):
        self._metrics: List = []  # vet: guarded-by(self._lock)
        self._lock = threading.Lock()

    def gauge(self, name: str, help_text: str, labels: Sequence[str] = ()) -> Gauge:
        metric = Gauge(f"{NAMESPACE}_{name}", help_text, labels)
        with self._lock:
            self._metrics.append(metric)
        return metric

    def counter(self, name: str, help_text: str, labels: Sequence[str] = ()) -> Counter:
        metric = Counter(f"{NAMESPACE}_{name}", help_text, labels)
        with self._lock:
            self._metrics.append(metric)
        return metric

    def histogram(
        self, name: str, help_text: str, labels: Sequence[str] = (), buckets=DURATION_BUCKETS
    ) -> Histogram:
        metric = Histogram(f"{NAMESPACE}_{name}", help_text, labels, buckets)
        with self._lock:
            self._metrics.append(metric)
        return metric

    def render(self) -> str:
        lines: List[str] = []
        with self._lock:
            for metric in self._metrics:
                lines.extend(metric.render())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()
