"""Process-level GC tuning for the controller binaries.

CPython's default gen-0 collection threshold (700 container allocations)
makes the cyclic collector run thousands of times during a pod storm —
every watch event, reconcile, and solve allocates dicts — and each run also
fires jax's registered GC callback. Raising the thresholds the way
long-running Python services do (the analogue of the GOGC headroom the Go
reference gets by default) removes ~25% of storm-drain wall clock
(bench.py bench_pod_storm) with bounded extra footprint: nearly all of this
workload's garbage is acyclic and freed by refcount regardless; the cyclic
collector only needs to catch rare reference cycles.

Applied at boot by cmd/controller.py and the solver sidecar, and by the
storm benchmark (which stands in for the controller binary).
"""

from __future__ import annotations

import gc

# gen0: collections per container-allocation delta; gen1/gen2 stay at the
# CPython defaults so full collections still happen on a bounded cadence —
# gen0 frequency is the whole storm win, and multiplying the older
# generations too would make surviving cycles effectively immortal in a
# long-running service.
GEN0_THRESHOLD = 100_000
GEN1_THRESHOLD = 10
GEN2_THRESHOLD = 10


def tune_gc() -> None:
    """Raise collector thresholds for long-running controller processes."""
    gc.collect()
    gc.set_threshold(GEN0_THRESHOLD, GEN1_THRESHOLD, GEN2_THRESHOLD)
