"""The one capped-exponential-backoff formula, plus the shared jitter.

Three delay ladders share the exponential shape — the kube retry envelope
(RetryPolicy.backoff_s, which layers jitter on top), the watch reconnect
backoff (KubeClient._watch_backoff_s), and the reconcile-loop error requeue
(ReconcileLoop._error_backoff_s) — so the formula lives once; a policy
change (e.g. extending jitter to the other ladders) edits one place.

`jittered_s` is the periodic-wait analogue: fixed cadences that several
replicas share (the leader-election renew and campaign polls) must not fire
in lockstep or every replica CASes the lease in the same instant — the
thundering herd the lease is supposed to serialize. Spreading each wait
uniformly over ±fraction decorrelates the replicas while keeping the mean
cadence.
"""

from __future__ import annotations

import random
from typing import Optional


def capped_backoff_s(base_s: float, cap_s: float, attempt: int) -> float:
    """min(cap, base * 2^(attempt-1)) — attempt is 1-based; values below 1
    clamp to the base."""
    return min(cap_s, base_s * (2 ** max(0, attempt - 1)))


def jittered_s(
    base_s: float, fraction: float = 0.2, rng: Optional[random.Random] = None
) -> float:
    """base spread uniformly over [base*(1-fraction), base*(1+fraction)].

    Pass an injected ``rng`` for deterministic tests; the module default is
    unseeded on purpose — decorrelation is the point.
    """
    roll = (rng or _rng).random()
    return base_s * (1.0 - fraction + 2.0 * fraction * roll)


_rng = random.Random()
