"""The one capped-exponential-backoff formula.

Three delay ladders share this shape — the kube retry envelope
(RetryPolicy.backoff_s, which layers jitter on top), the watch reconnect
backoff (KubeClient._watch_backoff_s), and the reconcile-loop error requeue
(ReconcileLoop._error_backoff_s) — so the formula lives once; a policy
change (e.g. extending jitter to the other ladders) edits one place.
"""

from __future__ import annotations


def capped_backoff_s(base_s: float, cap_s: float, attempt: int) -> float:
    """min(cap, base * 2^(attempt-1)) — attempt is 1-based; values below 1
    clamp to the base."""
    return min(cap_s, base_s * (2 ** max(0, attempt - 1)))
