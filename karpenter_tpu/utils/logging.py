"""Structured logging with live-reloadable level.

Ref: cmd/controller/main.go:101-115 — the reference builds a zap logger whose
level re-reads from the config-logging ConfigMap at runtime; named sub-loggers
per controller. We expose named loggers and a set_level() that takes effect
immediately (the runtime watches its config source and calls it).
"""

from __future__ import annotations

import logging
import sys

_ROOT_NAME = "karpenter"
_configured = False


def setup(level: str = "info") -> logging.Logger:
    global _configured
    root = logging.getLogger(_ROOT_NAME)
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s\t%(levelname)s\t%(name)s\t%(message)s"
            )
        )
        root.addHandler(handler)
        root.propagate = False
        _configured = True
    set_level(level)
    return root


def set_level(level: str) -> None:
    """Live level reload (ref: the config-logging ConfigMap watcher)."""
    logging.getLogger(_ROOT_NAME).setLevel(
        getattr(logging, level.upper(), logging.INFO)
    )


def get_level() -> str:
    """The current root level name, lowercased — what /debug/loglevel GETs.
    An unset root (no setup() yet) reads as the effective default, info."""
    level = logging.getLogger(_ROOT_NAME).level
    if level == logging.NOTSET:
        return "info"
    return logging.getLevelName(level).lower()


def named(name: str) -> logging.Logger:
    """Named sub-logger per controller (ref: provisioning/controller.go:65)."""
    return logging.getLogger(f"{_ROOT_NAME}.{name}")
