"""Tracing: spans around the provisioning pipeline and the solver boundary.

The reference ships no tracing at all (SURVEY.md §5 — only Prometheus
duration histograms); the rebuild adds it because the solve path now crosses
a process boundary (gRPC sidecar) and a device boundary (host↔TPU), where
aggregate histograms can't show *which* hop ate the latency budget.

Design: an in-process tracer with explicit context-manager spans. Spans
nest via a thread-local stack, live in a bounded ring buffer, and export as
Chrome trace events (chrome://tracing / Perfetto load them directly).
Enablement is environment-driven so production runs pay one branch per span
when disabled:

  KARPENTER_TRACE=1                 enable span collection
  KARPENTER_TRACE_FILE=/path.json   flush Chrome trace events there on exit
  KARPENTER_JAX_PROFILE_DIR=/path   capture a jax.profiler device trace
                                    around each solve (TPU-side timeline)

The TPU side rides jax.profiler: when a profile dir is set, solver spans
also enter a jax.profiler.TraceAnnotation so host spans and XLA device ops
line up in the same TensorBoard/Perfetto view.

Cross-process stitching: span timestamps are `perf_counter` readings, which
are incomparable across processes, so the exporter anchors every `ts` to the
wall clock via a per-tracer epoch offset (recorded in the export metadata)
— traces from the controller, the sidecar, and SPMD followers concatenate
into one aligned timeline. A trace id minted per provisioning batch
(new_trace_id / Tracer.trace) is stamped on every span recorded while it is
current and rides the solver RPC metadata and the SPMD broadcast header, so
one batch's spans correlate across all three processes.
"""

from __future__ import annotations

import atexit
import json
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.utils.clock import SYSTEM_CLOCK

_MAX_SPANS = 65536

# The span-name inventory: every TRACER.span(...) literal in production code
# must appear here (enforced by tools/vet's span-consistency checker, the
# tracing analogue of the metrics one-home discipline) — a renamed span that
# kept an old dashboard/trace query alive would otherwise drift silently.
SPAN_NAMES = (
    "provision.schedule",
    "provision.resolve",
    "provision.bind",
    "provision.solve",
    "provision.solve.constrained",
    "provision.solve.dispatch",
    "solve.device",
    "solve.device.batch",
    "solve.device.pipelined",
    "solver.rpc",
    "solver.rpc.stream",
    "solver.serve",
    "solver.serve.stream",
    "spmd.follower.step",
)

# gRPC metadata key carrying the batch trace id across the sidecar boundary.
TRACE_METADATA_KEY = "karpenter-trace-id"

_trace_rng = random.Random()


def new_trace_id() -> str:
    """A fresh 62-bit trace id as 16 hex chars (62 bits so the SPMD header
    can carry it as two non-negative int32 words)."""
    return f"{_trace_rng.getrandbits(62) | 1:016x}"


def trace_id_to_words(trace_id: Optional[str]) -> Tuple[int, int]:
    """(lo, hi) 31-bit words for fixed-shape int32 transports (SPMD header);
    (0, 0) means no trace."""
    if not trace_id:
        return 0, 0
    try:
        value = int(trace_id, 16)
    except ValueError:
        return 0, 0
    return value & 0x7FFFFFFF, (value >> 31) & 0x7FFFFFFF


def words_to_trace_id(lo: int, hi: int) -> Optional[str]:
    value = ((int(hi) & 0x7FFFFFFF) << 31) | (int(lo) & 0x7FFFFFFF)
    return f"{value:016x}" if value else None


@dataclass
class Span:
    name: str
    start_s: float
    duration_s: float = 0.0
    attributes: Dict[str, object] = field(default_factory=dict)
    parent: Optional[str] = None
    thread_id: int = 0
    thread_name: str = ""
    trace: str = ""


class _TraceContext:
    __slots__ = ("tracer", "trace_id", "_previous")

    def __init__(self, tracer: "Tracer", trace_id: Optional[str]):
        self.tracer = tracer
        self.trace_id = trace_id

    def __enter__(self):
        local = self.tracer._local
        self._previous = getattr(local, "trace", None)
        if self.trace_id is not None:
            local.trace = self.trace_id
        return self

    def __exit__(self, *exc):
        self.tracer._local.trace = self._previous
        return False


class Tracer:
    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = (
            enabled
            if enabled is not None
            else os.environ.get("KARPENTER_TRACE", "") not in ("", "0", "false")
        )
        self.profile_dir = os.environ.get("KARPENTER_JAX_PROFILE_DIR") or None
        # Wall-clock anchor for Chrome export: start_s values are
        # perf_counter readings (monotonic, process-local); adding this
        # offset rebases them onto the epoch so `ts` values from different
        # processes align in one merged timeline.
        self.epoch_offset_s = SYSTEM_CLOCK.now() - time.perf_counter()
        self._spans: deque = deque(maxlen=_MAX_SPANS)  # vet: guarded-by(self._lock)
        self._local = threading.local()
        self._lock = threading.Lock()

    # -- trace context -------------------------------------------------------

    def trace(self, trace_id: Optional[str]) -> _TraceContext:
        """Context manager making `trace_id` current for this thread; spans
        recorded inside carry it. None is a no-op (keeps any outer trace)."""
        return _TraceContext(self, trace_id)

    def current_trace(self) -> Optional[str]:
        return getattr(self._local, "trace", None)

    def current_parent(self) -> Optional[str]:
        """Name of the innermost open span on this thread, or None — parent
        attribution for spans recorded manually via record() (e.g. the
        pipelined RPC span, whose wire time is stamped off-thread)."""
        stack = self._stack()
        return stack[-1] if stack else None

    # -- spans ---------------------------------------------------------------

    def span(self, name: str, **attributes):
        """Context manager: times the block, records nesting."""
        return _SpanContext(self, name, attributes)

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- export --------------------------------------------------------------

    def chrome_trace_events(self) -> List[dict]:
        """Complete ('X') events in the Chrome trace event format, with `ts`
        rebased onto the wall clock (see epoch_offset_s)."""
        pid = os.getpid()
        return [
            {
                "name": span.name,
                "ph": "X",
                "ts": (self.epoch_offset_s + span.start_s) * 1e6,
                "dur": span.duration_s * 1e6,
                "pid": pid,
                "tid": span.thread_id,
                "args": {
                    **span.attributes,
                    "parent": span.parent or "",
                    "trace": span.trace,
                },
            }
            for span in self.spans()
        ]

    def chrome_trace_document(self) -> dict:
        """The full export: span events plus process_name/thread_name
        metadata ('M') events per pid/tid and the wall-clock anchor, so a
        merged multi-process trace labels every lane and stays aligned."""
        events = self.chrome_trace_events()
        pid = os.getpid()
        metadata: List[dict] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": f"karpenter-tpu:{pid}"},
            }
        ]
        named: Dict[int, str] = {}
        for span in self.spans():
            if span.thread_id not in named:
                named[span.thread_id] = span.thread_name or str(span.thread_id)
        metadata.extend(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": name},
            }
            for tid, name in sorted(named.items())
        )
        return {
            "traceEvents": metadata + events,
            "metadata": {
                "pid": pid,
                "clock_epoch_offset_s": self.epoch_offset_s,
                "clock_domain": "epoch-anchored perf_counter",
            },
        }

    def flush(self, path: Optional[str] = None) -> Optional[str]:
        path = path or os.environ.get("KARPENTER_TRACE_FILE")
        if not path:
            return None
        with open(path, "w") as f:
            json.dump(self.chrome_trace_document(), f)
        return path

    # -- stack ---------------------------------------------------------------

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack


class _SpanContext:
    __slots__ = ("tracer", "name", "attributes", "_start", "_jax_ctx")

    def __init__(self, tracer: Tracer, name: str, attributes: dict):
        self.tracer = tracer
        self.name = name
        self.attributes = attributes
        self._jax_ctx = None

    def __enter__(self):
        if not self.tracer.enabled:
            return self
        self._start = time.perf_counter()
        stack = self.tracer._stack()
        stack.append(self.name)
        if self.tracer.profile_dir is not None:
            # Line this host span up with XLA device ops in the jax profile.
            try:
                import jax.profiler

                self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
                self._jax_ctx.__enter__()
            except Exception:
                self._jax_ctx = None
        return self

    def set(self, **attributes) -> None:
        self.attributes.update(attributes)

    def __exit__(self, *exc):
        if not self.tracer.enabled:
            return False
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(*exc)
        stack = self.tracer._stack()
        stack.pop()
        current = threading.current_thread()
        self.tracer.record(
            Span(
                name=self.name,
                start_s=self._start,
                duration_s=time.perf_counter() - self._start,
                attributes=dict(self.attributes),
                parent=stack[-1] if stack else None,
                # Full idents: the old `& 0xFFFF` truncation collided thread
                # lanes in big pools, merging unrelated spans in the viewer.
                thread_id=threading.get_ident(),
                thread_name=current.name,
                trace=self.tracer.current_trace() or "",
            )
        )
        return False


class _ProfileSession:
    """jax.profiler capture around a block (KARPENTER_JAX_PROFILE_DIR)."""

    def __init__(self, tracer: Tracer):
        self.tracer = tracer
        self._active = False

    def __enter__(self):
        if self.tracer.profile_dir is not None:
            try:
                import jax.profiler

                jax.profiler.start_trace(self.tracer.profile_dir)
                self._active = True
            except Exception:
                self._active = False
        return self

    def __exit__(self, *exc):
        if self._active:
            import jax.profiler

            jax.profiler.stop_trace()
        return False


def device_profile(tracer: Optional[Tracer] = None) -> _ProfileSession:
    return _ProfileSession(tracer or TRACER)


# The process-wide tracer, mirroring metrics.REGISTRY. When a trace file is
# configured, collected spans flush there at interpreter exit (the documented
# KARPENTER_TRACE_FILE contract); flush() can also be called any time.
TRACER = Tracer()
if TRACER.enabled and os.environ.get("KARPENTER_TRACE_FILE"):
    atexit.register(TRACER.flush)
