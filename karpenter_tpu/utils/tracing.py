"""Tracing: spans around the provisioning pipeline and the solver boundary.

The reference ships no tracing at all (SURVEY.md §5 — only Prometheus
duration histograms); the rebuild adds it because the solve path now crosses
a process boundary (gRPC sidecar) and a device boundary (host↔TPU), where
aggregate histograms can't show *which* hop ate the latency budget.

Design: an in-process tracer with explicit context-manager spans. Spans
nest via a thread-local stack, live in a bounded ring buffer, and export as
Chrome trace events (chrome://tracing / Perfetto load them directly).
Enablement is environment-driven so production runs pay one branch per span
when disabled:

  KARPENTER_TRACE=1                 enable span collection
  KARPENTER_TRACE_FILE=/path.json   flush Chrome trace events there on exit
  KARPENTER_JAX_PROFILE_DIR=/path   capture a jax.profiler device trace
                                    around each solve (TPU-side timeline)

The TPU side rides jax.profiler: when a profile dir is set, solver spans
also enter a jax.profiler.TraceAnnotation so host spans and XLA device ops
line up in the same TensorBoard/Perfetto view.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_MAX_SPANS = 65536


@dataclass
class Span:
    name: str
    start_s: float
    duration_s: float = 0.0
    attributes: Dict[str, object] = field(default_factory=dict)
    parent: Optional[str] = None
    thread_id: int = 0


class Tracer:
    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = (
            enabled
            if enabled is not None
            else os.environ.get("KARPENTER_TRACE", "") not in ("", "0", "false")
        )
        self.profile_dir = os.environ.get("KARPENTER_JAX_PROFILE_DIR") or None
        self._spans: deque = deque(maxlen=_MAX_SPANS)  # vet: guarded-by(self._lock)
        self._local = threading.local()
        self._lock = threading.Lock()

    # -- spans ---------------------------------------------------------------

    def span(self, name: str, **attributes):
        """Context manager: times the block, records nesting."""
        return _SpanContext(self, name, attributes)

    def record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    def spans(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- export --------------------------------------------------------------

    def chrome_trace_events(self) -> List[dict]:
        """Complete ('X') events in the Chrome trace event format."""
        return [
            {
                "name": span.name,
                "ph": "X",
                "ts": span.start_s * 1e6,
                "dur": span.duration_s * 1e6,
                "pid": os.getpid(),
                "tid": span.thread_id,
                "args": {**span.attributes, "parent": span.parent or ""},
            }
            for span in self.spans()
        ]

    def flush(self, path: Optional[str] = None) -> Optional[str]:
        path = path or os.environ.get("KARPENTER_TRACE_FILE")
        if not path:
            return None
        with open(path, "w") as f:
            json.dump({"traceEvents": self.chrome_trace_events()}, f)
        return path

    # -- stack ---------------------------------------------------------------

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack


class _SpanContext:
    __slots__ = ("tracer", "name", "attributes", "_start", "_jax_ctx")

    def __init__(self, tracer: Tracer, name: str, attributes: dict):
        self.tracer = tracer
        self.name = name
        self.attributes = attributes
        self._jax_ctx = None

    def __enter__(self):
        if not self.tracer.enabled:
            return self
        self._start = time.perf_counter()
        stack = self.tracer._stack()
        stack.append(self.name)
        if self.tracer.profile_dir is not None:
            # Line this host span up with XLA device ops in the jax profile.
            try:
                import jax.profiler

                self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
                self._jax_ctx.__enter__()
            except Exception:
                self._jax_ctx = None
        return self

    def set(self, **attributes) -> None:
        self.attributes.update(attributes)

    def __exit__(self, *exc):
        if not self.tracer.enabled:
            return False
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(*exc)
        stack = self.tracer._stack()
        stack.pop()
        self.tracer.record(
            Span(
                name=self.name,
                start_s=self._start,
                duration_s=time.perf_counter() - self._start,
                attributes=dict(self.attributes),
                parent=stack[-1] if stack else None,
                thread_id=threading.get_ident() & 0xFFFF,
            )
        )
        return False


class _ProfileSession:
    """jax.profiler capture around a block (KARPENTER_JAX_PROFILE_DIR)."""

    def __init__(self, tracer: Tracer):
        self.tracer = tracer
        self._active = False

    def __enter__(self):
        if self.tracer.profile_dir is not None:
            try:
                import jax.profiler

                jax.profiler.start_trace(self.tracer.profile_dir)
                self._active = True
            except Exception:
                self._active = False
        return self

    def __exit__(self, *exc):
        if self._active:
            import jax.profiler

            jax.profiler.stop_trace()
        return False


def device_profile(tracer: Optional[Tracer] = None) -> _ProfileSession:
    return _ProfileSession(tracer or TRACER)


# The process-wide tracer, mirroring metrics.REGISTRY. When a trace file is
# configured, collected spans flush there at interpreter exit (the documented
# KARPENTER_TRACE_FILE contract); flush() can also be called any time.
TRACER = Tracer()
if TRACER.enabled and os.environ.get("KARPENTER_TRACE_FILE"):
    atexit.register(TRACER.flush)
