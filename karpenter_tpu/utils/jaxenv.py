"""Backend environment helpers shared by the test conftest, the driver
entry points, and the bench's device-unavailable fallback.

Under the axon TPU harness a sitecustomize registers the 'axon' PJRT
backend at interpreter start — before env vars can steer backend choice —
and selecting cpu via env alone then hangs in backend init. The working
sequence (update the already-imported jax config, then drop the axon
factory before any backend initializes) pokes a private jax attribute, so
it lives in exactly one place.
"""

from __future__ import annotations

import os


_PROBE_CODE = (
    "import jax, jax.numpy as jnp; jax.device_get(jnp.ones((8,)) + 1)"
)


def device_alive(timeout_s: float = 180.0, _probe_code: str = _PROBE_CODE) -> bool:
    """Probe the default accelerator in a SUBPROCESS with a hard timeout: a
    wedged tunnel hangs jax inside C (uninterruptible from Python), so the
    probe must be killable from outside. The child does exactly what a
    first device touch would do. On failure the child's stderr (which
    names the actual cause — import error, libtpu, backend init) is
    forwarded to this process's stderr."""
    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [sys.executable, "-c", _probe_code],
            timeout=timeout_s,
            capture_output=True,
        )
        if probe.returncode != 0:
            sys.stderr.write(
                "device probe failed:\n" + probe.stderr.decode(errors="replace")
            )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"device probe hung past {timeout_s}s (wedged tunnel?)\n")
        return False


def force_cpu_backend(host_devices: int | None = None, reset: bool = False):
    """Pin jax to the CPU backend in-process; returns the jax module.

    host_devices: also request an N-device virtual CPU mesh (must be set
    before the CPU backend initializes). reset: clear already-initialized
    backends first — needed when the caller already touched a device
    (e.g. counted jax.devices()) before deciding to switch.
    """
    if host_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={host_devices}"
        ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:  # pragma: no cover — jax internals moved; env still set
        pass
    if reset:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
    return jax
