"""Compatibility shim: the device-liveness probe and CPU-backend pin moved
into utils/backend_health, where the BackendHealth state machine owns the
single liveness verdict for the whole process (probe, TTL re-probe, metrics,
and degraded-mode routing). Import from there; these re-exports keep old
callers working."""

from karpenter_tpu.utils.backend_health import (  # noqa: F401
    device_alive,
    force_cpu_backend,
)
