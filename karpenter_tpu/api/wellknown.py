"""Well-known label vocabulary and framework constants.

Ref: pkg/apis/provisioning/v1alpha5/register.go:34-68 — the reference defines a
closed vocabulary of node labels that Requirements may constrain, plus
framework-owned annotations/taints/finalizers. We keep the same public names so
specs written for the reference remain meaningful, and add TPU-relevant
accelerator resource names.
"""

# API group (ours).
GROUP = "karpenter.tpu"

# --- Node label keys (the closed well-known set) ---------------------------
ZONE_LABEL = "topology.kubernetes.io/zone"
INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"
ARCH_LABEL = "kubernetes.io/arch"
OS_LABEL = "kubernetes.io/os"
HOSTNAME_LABEL = "kubernetes.io/hostname"
CAPACITY_TYPE_LABEL = "karpenter.sh/capacity-type"
PROVISIONER_NAME_LABEL = "karpenter.sh/provisioner-name"

WELL_KNOWN_LABELS = frozenset(
    {
        ZONE_LABEL,
        INSTANCE_TYPE_LABEL,
        ARCH_LABEL,
        OS_LABEL,
        HOSTNAME_LABEL,
        CAPACITY_TYPE_LABEL,
        PROVISIONER_NAME_LABEL,
    }
)

# Label domains users may not set directly on a Provisioner
# (ref: v1alpha5/register.go RestrictedLabels).
RESTRICTED_LABEL_DOMAINS = frozenset(
    {
        "kubernetes.io",
        "k8s.io",
        "karpenter.sh",
        GROUP,
    }
)
# Exceptions: well-known labels are settable via Requirements even though their
# domains are restricted for arbitrary labels.
RESTRICTED_LABEL_EXCEPTIONS = WELL_KNOWN_LABELS

# --- Capacity types --------------------------------------------------------
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_SPOT = "spot"

# --- Framework-owned markers ----------------------------------------------
NOT_READY_TAINT_KEY = "karpenter.sh/not-ready"
TERMINATION_FINALIZER = "karpenter.sh/termination"
DO_NOT_EVICT_ANNOTATION = "karpenter.sh/do-not-evict"
EMPTINESS_TIMESTAMP_ANNOTATION = "karpenter.sh/emptiness-timestamp"
# Interruption intent, stamped onto the victim Node BEFORE the provider event
# is acked — the durable record a restarted controller resumes the drain from
# (controllers/interruption.py).
INTERRUPTION_KIND_ANNOTATION = "karpenter.sh/interruption-kind"
INTERRUPTION_DEADLINE_ANNOTATION = "karpenter.sh/interruption-deadline"
# Bumped every time a pod is displaced back to pending (interruption drain).
# Part of the launch identity: a displaced pod's replacement launch must be a
# DIFFERENT logical launch than the purchase that backed its old node, or a
# restart-idempotent provider would "adopt" the dying instance and rebind the
# pod onto the node being reclaimed.
RESCHEDULE_EPOCH_ANNOTATION = "karpenter.sh/reschedule-epoch"
# Consolidation intent ("delete" | "replace"), stamped onto the victim Node
# BEFORE any pod is displaced — the durable record a restarted controller
# resumes the drain from (controllers/consolidation.py). Doubles as the
# in-flight marker that caps concurrent voluntary disruption.
CONSOLIDATION_ACTION_ANNOTATION = "karpenter.sh/consolidation-action"
# The canonical hash of the owning Provisioner's constraint envelope, stamped
# at node registration (controllers/provisioning.py) and back-filled on
# legacy/adopted nodes by the node reconciler — never treated as drift while
# missing. The drift sweep compares it against the CURRENT spec hash
# (karpenter_tpu/drift/).
PROVISIONER_HASH_ANNOTATION = "karpenter.sh/provisioner-hash"
# Drift intent (the drift KIND: "spec" | "provider" | "expired"), stamped onto
# the victim Node BEFORE any pod is displaced — the durable record a restarted
# controller resumes the rolling replacement from (controllers/drift.py).
# Doubles as the in-flight marker the shared disruption ledger counts.
DRIFT_ACTION_ANNOTATION = "karpenter.sh/drift-action"

# --- Resource names --------------------------------------------------------
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"
RESOURCE_NVIDIA_GPU = "nvidia.com/gpu"
RESOURCE_AMD_GPU = "amd.com/gpu"
RESOURCE_AWS_NEURON = "aws.amazon.com/neuron"
RESOURCE_AWS_POD_ENI = "vpc.amazonaws.com/pod-eni"
RESOURCE_GOOGLE_TPU = "google.com/tpu"

# Accelerator resources: a pod requesting any of these must land on an
# instance type that offers it, and instance types offering them are avoided
# for pods that don't (anti-waste; ref: binpacking/packable.go:220-246).
ACCELERATOR_RESOURCES = (
    RESOURCE_NVIDIA_GPU,
    RESOURCE_AMD_GPU,
    RESOURCE_AWS_NEURON,
    RESOURCE_GOOGLE_TPU,
)

# The dense-resource dimension order used by every tensor kernel.
# Units chosen so float32 stays exact over realistic magnitudes:
# cpu in millicores, memory in MiB, counts for everything else.
RESOURCE_DIMS = (
    RESOURCE_CPU,          # millicores
    RESOURCE_MEMORY,       # MiB
    RESOURCE_PODS,         # count
    RESOURCE_NVIDIA_GPU,   # count
    RESOURCE_AMD_GPU,      # count
    RESOURCE_AWS_NEURON,   # count
    RESOURCE_GOOGLE_TPU,   # count
    RESOURCE_AWS_POD_ENI,  # count
)
RESOURCE_DIM_INDEX = {name: i for i, name in enumerate(RESOURCE_DIMS)}
NUM_RESOURCE_DIMS = len(RESOURCE_DIMS)

# Scaling applied when densifying a ResourceList into the RESOURCE_DIMS vector.
CPU_SCALE = 1000.0       # cores -> millicores
MEMORY_SCALE = 1.0 / (1024.0 * 1024.0)  # bytes -> MiB
