"""JSON (de)serialization for the API types — the CRD wire format.

Ref: the reference's types are kube CRDs serialized by apimachinery
(zz_generated.deepcopy.go et al). We keep the same field names as the
v1alpha5 YAML so existing Provisioner manifests translate directly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from karpenter_tpu.api.pods import PodSpec, PreferredTerm, TopologySpreadConstraint
from karpenter_tpu.api.provisioner import (
    Constraints,
    Limits,
    Provisioner,
    ProvisionerSpec,
    ProvisionerStatus,
)
from karpenter_tpu.api.requirements import Requirement, Requirements
from karpenter_tpu.api.taints import Taint, Toleration


def requirement_to_dict(requirement: Requirement) -> Dict[str, Any]:
    return {
        "key": requirement.key,
        "operator": requirement.operator,
        "values": list(requirement.values),
    }


def requirement_from_dict(data: Dict[str, Any]) -> Requirement:
    return Requirement(
        key=data["key"],
        operator=data["operator"],
        values=tuple(data.get("values", ())),
    )


def taint_to_dict(taint: Taint) -> Dict[str, Any]:
    return {"key": taint.key, "value": taint.value, "effect": taint.effect}


def taint_from_dict(data: Dict[str, Any]) -> Taint:
    return Taint(
        key=data["key"],
        value=data.get("value", ""),
        effect=data.get("effect", "NoSchedule"),
    )


def provisioner_to_dict(provisioner: Provisioner) -> Dict[str, Any]:
    spec = provisioner.spec
    constraints = spec.constraints
    out: Dict[str, Any] = {
        "apiVersion": "karpenter.tpu/v1alpha1",
        "kind": "Provisioner",
        "metadata": {"name": provisioner.name, "uid": provisioner.uid},
        "spec": {
            "labels": dict(constraints.labels),
            "taints": [taint_to_dict(t) for t in constraints.taints],
            "requirements": [
                requirement_to_dict(r) for r in constraints.requirements
            ],
        },
        "status": {
            "resources": dict(provisioner.status.resources),
            "lastScaleTime": provisioner.status.last_scale_time,
            "conditions": [
                {"type": kind, "status": "True" if value else "False"}
                for kind, value in sorted(provisioner.status.conditions.items())
            ],
        },
    }
    if constraints.provider is not None:
        out["spec"]["provider"] = constraints.provider
    if spec.ttl_seconds_after_empty is not None:
        out["spec"]["ttlSecondsAfterEmpty"] = spec.ttl_seconds_after_empty
    if spec.ttl_seconds_until_expired is not None:
        out["spec"]["ttlSecondsUntilExpired"] = spec.ttl_seconds_until_expired
    if spec.limits is not None:
        out["spec"]["limits"] = {"resources": dict(spec.limits.resources)}
    if spec.weight:
        out["spec"]["weight"] = spec.weight
    return out


def provisioner_from_dict(data: Dict[str, Any]) -> Provisioner:
    metadata = data.get("metadata", {})
    spec_data = data.get("spec", {})
    limits_data = spec_data.get("limits")
    spec = ProvisionerSpec(
        constraints=Constraints(
            labels=dict(spec_data.get("labels", {})),
            taints=[taint_from_dict(t) for t in spec_data.get("taints", [])],
            requirements=Requirements(
                requirement_from_dict(r) for r in spec_data.get("requirements", [])
            ),
            provider=spec_data.get("provider"),
        ),
        ttl_seconds_after_empty=spec_data.get("ttlSecondsAfterEmpty"),
        ttl_seconds_until_expired=spec_data.get("ttlSecondsUntilExpired"),
        limits=Limits(resources=dict(limits_data.get("resources", {})))
        if limits_data
        else None,
        weight=int(spec_data.get("weight", 0)),
    )
    provisioner = Provisioner(name=metadata.get("name", ""), spec=spec)
    if metadata.get("uid"):
        provisioner.uid = metadata["uid"]
    status = data.get("status", {})
    provisioner.status = ProvisionerStatus(
        last_scale_time=status.get("lastScaleTime"),
        conditions={
            c.get("type", ""): c.get("status") == "True"
            for c in status.get("conditions", [])
            if c.get("type")
        },
        resources=dict(status.get("resources", {})),
    )
    return provisioner


def pod_to_dict(pod: PodSpec) -> Dict[str, Any]:
    return {
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "uid": pod.uid,
            "labels": dict(pod.labels),
            "annotations": dict(pod.annotations),
            "creationTimestamp": pod.created_at,
        },
        "spec": {
            "requests": dict(pod.requests),
            "nodeSelector": dict(pod.node_selector),
            "requiredTerms": [
                [requirement_to_dict(r) for r in term] for term in pod.required_terms
            ],
            # matchFields terms round-trip so selection can REJECT pods using
            # them (ref: selection/controller.go validate:108-159) — dropping
            # them here would silently accept what the reference refuses.
            "matchFieldsTerms": [dict(t) for t in pod.match_fields_terms],
            "podAffinityTerms": [dict(t) for t in pod.pod_affinity_terms],
            "podAntiAffinityTerms": [dict(t) for t in pod.pod_anti_affinity_terms],
            "preferredTerms": [
                {
                    "weight": term.weight,
                    "requirements": [requirement_to_dict(r) for r in term.requirements],
                }
                for term in pod.preferred_terms
            ],
            "tolerations": [
                {
                    "key": t.key,
                    "operator": t.operator,
                    "value": t.value,
                    "effect": t.effect,
                }
                for t in pod.tolerations
            ],
            "topologySpreadConstraints": [
                {
                    "maxSkew": c.max_skew,
                    "topologyKey": c.topology_key,
                    "whenUnsatisfiable": c.when_unsatisfiable,
                    "matchLabels": dict(c.match_labels),
                }
                for c in pod.topology_spread
            ],
            "priorityClassName": pod.priority_class_name,
            "ownerKind": pod.owner_kind,
        },
        "status": {
            "phase": pod.phase,
            "nodeName": pod.node_name,
            "unschedulable": pod.unschedulable,
            "deletionTimestamp": pod.deletion_timestamp,
        },
    }


def pod_from_dict(data: Dict[str, Any]) -> PodSpec:
    metadata = data.get("metadata", {})
    spec = data.get("spec", {})
    status = data.get("status", {})
    pod = PodSpec(
        name=metadata.get("name", ""),
        namespace=metadata.get("namespace", "default"),
        labels=dict(metadata.get("labels", {})),
        annotations=dict(metadata.get("annotations", {})),
        requests=dict(spec.get("requests", {})),
        node_selector=dict(spec.get("nodeSelector", {})),
        required_terms=[
            [requirement_from_dict(r) for r in term]
            for term in spec.get("requiredTerms", [])
        ],
        match_fields_terms=[dict(t) for t in spec.get("matchFieldsTerms", [])],
        pod_affinity_terms=[dict(t) for t in spec.get("podAffinityTerms", [])],
        pod_anti_affinity_terms=[
            dict(t) for t in spec.get("podAntiAffinityTerms", [])
        ],
        preferred_terms=[
            PreferredTerm(
                weight=term["weight"],
                requirements=[
                    requirement_from_dict(r) for r in term.get("requirements", [])
                ],
            )
            for term in spec.get("preferredTerms", [])
        ],
        tolerations=[
            Toleration(
                key=t.get("key", ""),
                operator=t.get("operator", "Equal"),
                value=t.get("value", ""),
                effect=t.get("effect", ""),
            )
            for t in spec.get("tolerations", [])
        ],
        topology_spread=[
            TopologySpreadConstraint(
                max_skew=c["maxSkew"],
                topology_key=c["topologyKey"],
                when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
                match_labels=dict(c.get("matchLabels", {})),
            )
            for c in spec.get("topologySpreadConstraints", [])
        ],
        priority_class_name=spec.get("priorityClassName", ""),
        owner_kind=spec.get("ownerKind"),
        phase=status.get("phase", "Pending"),
        node_name=status.get("nodeName"),
        unschedulable=status.get("unschedulable", False),
        deletion_timestamp=status.get("deletionTimestamp"),
        created_at=metadata.get("creationTimestamp"),
    )
    if metadata.get("uid"):
        pod.uid = metadata["uid"]
    return pod
