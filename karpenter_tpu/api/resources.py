"""Resource quantities and ResourceList arithmetic.

Ref: pkg/utils/resources/resources.go — the reference leans on k8s
resource.Quantity; we implement the subset of quantity syntax the provisioning
path actually exercises (decimal + binary SI suffixes, millicores) on plain
floats, plus merge/sum/fit predicates over dict-shaped resource lists.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Mapping, Union

# A parsed quantity is a float in base units (cores for cpu, bytes for memory,
# counts otherwise).
Quantity = float

# "cpu": 1.5, "memory": 2 * 1024**3, ...
ResourceList = Dict[str, Quantity]

_BINARY_SUFFIX = {
    "Ki": 1024.0,
    "Mi": 1024.0**2,
    "Gi": 1024.0**3,
    "Ti": 1024.0**4,
    "Pi": 1024.0**5,
    "Ei": 1024.0**6,
}
_DECIMAL_SUFFIX = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "": 1.0,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}

_QUANTITY_RE = re.compile(
    r"^\s*([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*"
    r"(Ki|Mi|Gi|Ti|Pi|Ei|n|u|m|k|M|G|T|P|E)?\s*$"
)


def parse_quantity(value: Union[str, int, float]) -> Quantity:
    """Parse a k8s-style quantity ("100m", "512Mi", "2", 1.5) into a float."""
    if isinstance(value, (int, float)):
        return float(value)
    match = _QUANTITY_RE.match(value)
    if match is None:
        raise ValueError(f"invalid quantity {value!r}")
    number, suffix = match.groups()
    scale = _BINARY_SUFFIX.get(suffix or "", None)
    if scale is None:
        scale = _DECIMAL_SUFFIX[suffix or ""]
    return float(number) * scale


def parse_resource_list(raw: Mapping[str, Union[str, int, float]]) -> ResourceList:
    return {key: parse_quantity(value) for key, value in raw.items()}


def add_resources(*lists: Mapping[str, Quantity]) -> ResourceList:
    """Union of resource lists, summing overlapping keys (ref: resources.go Merge)."""
    out: ResourceList = {}
    for rl in lists:
        for key, value in rl.items():
            out[key] = out.get(key, 0.0) + value
    return out


def subtract_resources(
    a: Mapping[str, Quantity], b: Mapping[str, Quantity]
) -> ResourceList:
    out: ResourceList = dict(a)
    for key, value in b.items():
        out[key] = out.get(key, 0.0) - value
    return out


def scale_resources(a: Mapping[str, Quantity], factor: float) -> ResourceList:
    return {key: value * factor for key, value in a.items()}


def fits_within(request: Mapping[str, Quantity], capacity: Mapping[str, Quantity]) -> bool:
    """True iff every requested resource is available in capacity."""
    for key, value in request.items():
        if value <= 0:
            continue
        if capacity.get(key, 0.0) < value:
            return False
    return True


def max_resources(*lists: Mapping[str, Quantity]) -> ResourceList:
    """Per-key maximum — used for pod effective request = max(init, containers)."""
    out: ResourceList = {}
    for rl in lists:
        for key, value in rl.items():
            out[key] = max(out.get(key, 0.0), value)
    return out


def sum_requests(requests: Iterable[Mapping[str, Quantity]]) -> ResourceList:
    return add_resources(*list(requests))


def nonzero(rl: Mapping[str, Quantity]) -> ResourceList:
    return {key: value for key, value in rl.items() if value > 0}
