"""Provisioner — the root configuration object of the framework.

Ref: pkg/apis/provisioning/v1alpha5/provisioner.go, constraints.go, limits.go,
provisioner_status.go. A Provisioner declares the constraint envelope
(labels, taints, requirements, vendor provider config), lifecycle TTLs, and
resource limits; the provisioning controller runs one batching loop per
Provisioner.
"""

from __future__ import annotations

import copy
import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional

from karpenter_tpu.api.pods import PodSpec
from karpenter_tpu.api.requirements import SUPPORTED_OPERATORS, Requirements
from karpenter_tpu.api.resources import ResourceList, parse_resource_list
from karpenter_tpu.api.taints import Taint, taints_tolerate_pod

_uid_counter = itertools.count(1)


class PodIncompatibleError(Exception):
    """Pod cannot be satisfied by this provisioner's constraints."""


@dataclass
class Limits:
    """Caps total resources provisioned (ref: limits.go:29-41)."""

    resources: ResourceList = field(default_factory=dict)

    def __post_init__(self):
        if self.resources:
            self.resources = parse_resource_list(self.resources)

    def exceeded_by(self, usage: Mapping[str, float]) -> Optional[str]:
        """Return a human reason if usage exceeds any limit, else None."""
        for key, limit in self.resources.items():
            used = usage.get(key, 0.0)
            if used >= limit:
                return f"{key} resource usage of {used:g} exceeds limit of {limit:g}"
        return None


@dataclass
class Constraints:
    """The constraint envelope applied to every node a provisioner creates
    (ref: constraints.go:25-72)."""

    labels: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    requirements: Requirements = field(default_factory=Requirements)
    # Opaque vendor extension (ref: Provider *runtime.RawExtension). Decoded by
    # the active cloud provider.
    provider: Optional[Dict[str, Any]] = None

    def effective_requirements(self) -> Requirements:
        """Requirements plus labels lifted into In-requirements
        (ref: controller.go:97-101 adds LabelRequirements before solving)."""
        return self.requirements.merge(Requirements.from_labels(self.labels))

    def validate_pod(self, pod: PodSpec) -> None:
        """Raise PodIncompatibleError unless the pod tolerates our taints and
        its scheduling requirements intersect ours (ref: constraints.go:43-63).

        Pods using operators outside In/NotIn are rejected here as
        incompatible rather than crashing the evaluator — the reference
        filters them earlier at selection (selection/controller.go:130-141),
        and the selection controller does too; this is the backstop.
        """
        if not taints_tolerate_pod(self.taints, pod.tolerations):
            raise PodIncompatibleError(
                f"pod {pod.namespace}/{pod.name} does not tolerate provisioner taints"
            )
        theirs = pod.scheduling_requirements()
        for requirement in theirs:
            if requirement.operator not in SUPPORTED_OPERATORS:
                raise PodIncompatibleError(
                    f"pod {pod.namespace}/{pod.name} uses unsupported operator "
                    f"{requirement.operator!r}"
                )
        ours = self.effective_requirements()
        if not ours.compatible_with(theirs):
            raise PodIncompatibleError(
                f"pod {pod.namespace}/{pod.name} requirements incompatible with provisioner"
            )

    def tighten(self, pod: PodSpec) -> "Constraints":
        """Constraints ∧ pod requirements, consolidated to well-known keys
        (ref: constraints.go Tighten:65-72). The result is the per-schedule
        constraint set handed to the solver."""
        tightened = (
            self.effective_requirements()
            .merge(pod.scheduling_requirements())
            .consolidate()
            .well_known()
        )
        return Constraints(
            labels=dict(self.labels),
            taints=list(self.taints),
            requirements=tightened,
            provider=copy.deepcopy(self.provider),
        )


@dataclass
class ProvisionerSpec:
    constraints: Constraints = field(default_factory=Constraints)
    ttl_seconds_after_empty: Optional[float] = None
    ttl_seconds_until_expired: Optional[float] = None
    limits: Optional[Limits] = None
    # Selection priority among provisioners that both match a pod: higher
    # weight wins, name breaks ties (real-Karpenter `.spec.weight`). Excluded
    # from the drift hash — re-weighting must not roll a fleet.
    weight: int = 0


@dataclass
class ProvisionerStatus:
    """Ref: provisioner_status.go:22-50."""

    last_scale_time: Optional[float] = None
    resources: ResourceList = field(default_factory=dict)
    conditions: Dict[str, bool] = field(default_factory=dict)


@dataclass
class Provisioner:
    name: str
    spec: ProvisionerSpec = field(default_factory=ProvisionerSpec)
    status: ProvisionerStatus = field(default_factory=ProvisionerStatus)
    uid: str = ""
    deletion_timestamp: Optional[float] = None

    def __post_init__(self):
        if not self.uid:
            self.uid = f"provisioner-uid-{next(_uid_counter)}"
