"""Node-selector requirement set algebra.

Ref: pkg/apis/provisioning/v1alpha5/requirements.go — the reference decorates
[]NodeSelectorRequirement with a per-key set evaluator: the allowed values for
a key are the intersection of all In sets minus every NotIn value; a key with
no In requirement is unconstrained (complement set). Only the In / NotIn
operators are supported anywhere in the provisioning path
(ref: selection/controller.go:130-141 rejects the rest).

We represent each key's allowed values as a KeySet — either a finite set
(`complement=False`) or "everything except" (`complement=True`) — which makes
intersection/compatibility exact without enumerating a universe.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import FrozenSet, Iterable, List, Mapping, Optional, Tuple

from karpenter_tpu.api import wellknown

IN = "In"
NOT_IN = "NotIn"
SUPPORTED_OPERATORS = (IN, NOT_IN)


@dataclass(frozen=True)
class Requirement:
    """One node-selector term: key op [values]."""

    key: str
    operator: str
    values: Tuple[str, ...]

    def __post_init__(self):
        object.__setattr__(self, "values", tuple(self.values))

    @staticmethod
    def in_(key: str, values: Iterable[str]) -> "Requirement":
        return Requirement(key=key, operator=IN, values=tuple(values))

    @staticmethod
    def not_in(key: str, values: Iterable[str]) -> "Requirement":
        return Requirement(key=key, operator=NOT_IN, values=tuple(values))


@dataclass(frozen=True)
class KeySet:
    """Allowed values for one key: a finite set, or a complement set."""

    values: FrozenSet[str]
    complement: bool = False  # True => allowed = (universe - values)

    @staticmethod
    def any() -> "KeySet":
        return KeySet(values=frozenset(), complement=True)

    @staticmethod
    def of(values: Iterable[str]) -> "KeySet":
        return KeySet(values=frozenset(values), complement=False)

    def contains(self, value: str) -> bool:
        return (value not in self.values) if self.complement else (value in self.values)

    def intersect(self, other: "KeySet") -> "KeySet":
        if self.complement and other.complement:
            return KeySet(values=self.values | other.values, complement=True)
        if self.complement:
            return KeySet(values=other.values - self.values, complement=False)
        if other.complement:
            return KeySet(values=self.values - other.values, complement=False)
        return KeySet(values=self.values & other.values, complement=False)

    def is_empty(self) -> bool:
        return not self.complement and not self.values

    def is_any(self) -> bool:
        return self.complement and not self.values

    def finite_values(self) -> Optional[FrozenSet[str]]:
        """The allowed values if finite, else None (complement sets are infinite)."""
        return None if self.complement else self.values


class Requirements:
    """An ordered collection of Requirements with set-algebra evaluation."""

    def __init__(self, requirements: Iterable[Requirement] = ()):  # noqa: D401
        self._requirements: List[Requirement] = list(requirements)

    # --- construction ------------------------------------------------------

    @staticmethod
    def from_labels(labels: Mapping[str, str]) -> "Requirements":
        """Each label k=v becomes `k In [v]` (ref: requirements.go LabelRequirements)."""
        return Requirements(
            Requirement.in_(key, [value]) for key, value in sorted(labels.items())
        )

    def add(self, *requirements: Requirement) -> "Requirements":
        """Return a new Requirements with extra terms appended."""
        return Requirements([*self._requirements, *requirements])

    def merge(self, other: "Requirements") -> "Requirements":
        return Requirements([*self._requirements, *other._requirements])

    # --- evaluation --------------------------------------------------------

    def keys(self) -> List[str]:
        seen, out = set(), []
        for requirement in self._requirements:
            if requirement.key not in seen:
                seen.add(requirement.key)
                out.append(requirement.key)
        return out

    def allowed(self, key: str) -> KeySet:
        """Allowed values for key: ∩(In sets) minus ∪(NotIn values)."""
        result = KeySet.any()
        for requirement in self._requirements:
            if requirement.key != key:
                continue
            if requirement.operator == IN:
                result = result.intersect(KeySet.of(requirement.values))
            elif requirement.operator == NOT_IN:
                result = result.intersect(
                    KeySet(values=frozenset(requirement.values), complement=True)
                )
            else:
                raise ValueError(
                    f"unsupported operator {requirement.operator!r} for key {requirement.key!r}"
                )
        return result

    def consolidate(self) -> "Requirements":
        """One canonical requirement per key (ref: requirements.go Consolidate).

        Keys whose allowed set is finite collapse to a single In; complement
        sets collapse to a single NotIn. Empty finite sets are preserved as an
        In with no values (the unsatisfiable requirement), matching the
        reference's behavior of surfacing conflicts rather than dropping them.
        """
        out: List[Requirement] = []
        for key in self.keys():
            keyset = self.allowed(key)
            if keyset.complement:
                if keyset.values:
                    out.append(Requirement.not_in(key, sorted(keyset.values)))
                # is_any(): unconstrained — no requirement emitted.
            else:
                out.append(Requirement.in_(key, sorted(keyset.values)))
        return Requirements(out)

    def compatible_with(self, other: "Requirements") -> bool:
        """True iff for every key constrained by both, the intersection is nonempty."""
        for key in set(self.keys()) | set(other.keys()):
            if self.allowed(key).intersect(other.allowed(key)).is_empty():
                return False
        return True

    def satisfied_by_labels(self, labels: Mapping[str, str]) -> bool:
        """True iff a node with these labels satisfies every constrained key.

        A key constrained to a finite set requires the label to be present and
        allowed; a complement (NotIn-only) key tolerates an absent label.
        """
        for key in self.keys():
            keyset = self.allowed(key)
            if keyset.is_any():
                continue
            value = labels.get(key)
            if value is None:
                if not keyset.complement:
                    return False
                continue
            if not keyset.contains(value):
                return False
        return True

    # --- well-known accessors (ref: requirements.go:27-45) ------------------

    def _finite(self, key: str) -> Optional[FrozenSet[str]]:
        return self.allowed(key).finite_values()

    def zones(self) -> Optional[FrozenSet[str]]:
        return self._finite(wellknown.ZONE_LABEL)

    def instance_types(self) -> Optional[FrozenSet[str]]:
        return self._finite(wellknown.INSTANCE_TYPE_LABEL)

    def architectures(self) -> Optional[FrozenSet[str]]:
        return self._finite(wellknown.ARCH_LABEL)

    def operating_systems(self) -> Optional[FrozenSet[str]]:
        return self._finite(wellknown.OS_LABEL)

    def capacity_types(self) -> Optional[FrozenSet[str]]:
        return self._finite(wellknown.CAPACITY_TYPE_LABEL)

    def well_known(self) -> "Requirements":
        """Only requirements on well-known keys (ref: requirements.go WellKnown)."""
        return Requirements(
            r for r in self._requirements if r.key in wellknown.WELL_KNOWN_LABELS
        )

    # --- plumbing ----------------------------------------------------------

    def __iter__(self):
        return iter(self._requirements)

    def __len__(self):
        return len(self._requirements)

    def __eq__(self, other):
        if not isinstance(other, Requirements):
            return NotImplemented
        return self._requirements == other._requirements

    def __repr__(self):
        terms = ", ".join(
            f"{r.key} {r.operator} {list(r.values)}" for r in self._requirements
        )
        return f"Requirements({terms})"

    def canonical_key(self) -> Tuple:
        """Hashable canonical form — used for isomorphic-constraint grouping
        (ref: scheduling/scheduler.go:88-126 hashes constraints)."""
        parts = []
        for key in sorted(self.keys()):
            keyset = self.allowed(key)
            parts.append((key, keyset.complement, tuple(sorted(keyset.values))))
        return tuple(parts)
