"""Typed spec model for provisioning (ref: pkg/apis/provisioning/v1alpha5)."""

from karpenter_tpu.api.resources import (
    Quantity,
    parse_quantity,
    ResourceList,
    add_resources,
    subtract_resources,
    fits_within,
)
from karpenter_tpu.api.requirements import Requirement, Requirements, IN, NOT_IN
from karpenter_tpu.api.taints import Taint, Toleration, taints_tolerate_pod, taints_for_pod
from karpenter_tpu.api.pods import PodSpec, TopologySpreadConstraint
from karpenter_tpu.api.provisioner import (
    Provisioner,
    ProvisionerSpec,
    ProvisionerStatus,
    Constraints,
    Limits,
)
from karpenter_tpu.api import wellknown

__all__ = [
    "Quantity",
    "parse_quantity",
    "ResourceList",
    "add_resources",
    "subtract_resources",
    "fits_within",
    "Requirement",
    "Requirements",
    "IN",
    "NOT_IN",
    "Taint",
    "Toleration",
    "taints_tolerate_pod",
    "taints_for_pod",
    "PodSpec",
    "TopologySpreadConstraint",
    "Provisioner",
    "ProvisionerSpec",
    "ProvisionerStatus",
    "Constraints",
    "Limits",
    "wellknown",
]
