"""Provisioner validation and defaulting.

Ref: pkg/apis/provisioning/v1alpha5/provisioner_validation.go:30-158 and
provisioner_defaults.go. The reference runs these in admission webhooks; we run
them at Provisioner apply time in the provisioning controller. Cloud providers
install extra behavior through the pluggable DEFAULT_HOOK / VALIDATE_HOOK
(ref: register.go:66-68), set by cloudprovider.registry at startup.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.provisioner import Provisioner
from karpenter_tpu.api.requirements import SUPPORTED_OPERATORS
from karpenter_tpu.api.taints import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
)


class ValidationError(Exception):
    pass


# Pluggable cloud-provider hooks (ref: v1alpha5/register.go DefaultHook/ValidateHook).
DEFAULT_HOOK: Optional[Callable[[Provisioner], None]] = None
VALIDATE_HOOK: Optional[Callable[[Provisioner], None]] = None

_QUALIFIED_NAME_RE = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")
_LABEL_VALUE_RE = re.compile(r"^([A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?)?$")
_DNS_LABEL_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_VALID_EFFECTS = {EFFECT_NO_SCHEDULE, EFFECT_PREFER_NO_SCHEDULE, EFFECT_NO_EXECUTE}


def _label_key_domain(key: str) -> str:
    return key.rsplit("/", 1)[0] if "/" in key else ""


def _validate_label_key(key: str, errors: List[str], where: str) -> None:
    name = key.rsplit("/", 1)[-1]
    if not name or not _QUALIFIED_NAME_RE.match(name) or len(name) > 63:
        errors.append(f"{where}: invalid label key {key!r}")
    # The optional prefix must be a DNS subdomain (kube IsQualifiedName).
    domain = _label_key_domain(key)
    if domain and (
        len(domain) > 253
        or not all(_DNS_LABEL_RE.match(part) for part in domain.split("."))
    ):
        errors.append(f"{where}: invalid label key domain {domain!r}")


def default_provisioner(provisioner: Provisioner) -> None:
    if DEFAULT_HOOK is not None:
        DEFAULT_HOOK(provisioner)


def _validate_weight(weight, errors: List[str]) -> None:
    # Weight: real Karpenter bounds .spec.weight to [0, 100] (0 = unset).
    if not isinstance(weight, int) or isinstance(weight, bool):
        errors.append(f"weight must be an integer, got {weight!r}")
    elif not 0 <= weight <= 100:
        errors.append(f"weight must be in [0, 100], got {weight}")


def validate_provisioner(provisioner: Provisioner) -> None:
    """Raise ValidationError listing every problem found."""
    errors: List[str] = []
    if not provisioner.name or len(provisioner.name) > 63:
        errors.append("metadata.name must be 1-63 characters")
    spec = provisioner.spec

    for ttl_name, ttl in (
        ("ttlSecondsAfterEmpty", spec.ttl_seconds_after_empty),
        ("ttlSecondsUntilExpired", spec.ttl_seconds_until_expired),
    ):
        if ttl is not None and ttl < 0:
            errors.append(f"{ttl_name} must be non-negative, got {ttl}")

    _validate_weight(spec.weight, errors)

    # Labels: restricted domains may not be set directly (ref: validation.go
    # restricted-label check); values must be legal.
    for key, value in spec.constraints.labels.items():
        _validate_label_key(key, errors, "labels")
        if not _LABEL_VALUE_RE.match(value) or len(value) > 63:
            errors.append(f"labels: invalid value {value!r} for key {key!r}")
        domain = _label_key_domain(key)
        if key not in wellknown.RESTRICTED_LABEL_EXCEPTIONS and any(
            domain == d or domain.endswith("." + d)
            for d in wellknown.RESTRICTED_LABEL_DOMAINS
        ):
            errors.append(f"labels: domain {domain!r} is restricted (key {key!r})")

    for taint in spec.constraints.taints:
        _validate_label_key(taint.key, errors, "taints")
        if taint.effect not in _VALID_EFFECTS:
            errors.append(f"taints: invalid effect {taint.effect!r}")

    # Requirements: only In/NotIn over well-known keys
    # (ref: provisioner_validation.go:120-158).
    for requirement in spec.constraints.requirements:
        if requirement.key not in wellknown.WELL_KNOWN_LABELS:
            errors.append(
                f"requirements: key {requirement.key!r} is not in the well-known set"
            )
        if requirement.operator not in SUPPORTED_OPERATORS:
            errors.append(
                f"requirements: operator {requirement.operator!r} not supported "
                f"(only {list(SUPPORTED_OPERATORS)})"
            )

    if spec.limits is not None:
        for key, quantity in spec.limits.resources.items():
            if quantity < 0:
                errors.append(f"limits: {key} must be non-negative")

    if errors:
        raise ValidationError("; ".join(errors))

    if VALIDATE_HOOK is not None:
        VALIDATE_HOOK(provisioner)
