"""Pod model — the slice of a kube Pod the provisioning path consumes.

Ref: the reference operates on v1.Pod via helpers in pkg/utils/pod and
v1alpha5.Requirements.PodRequirements (requirements.go:58-76). We model only
the fields those paths read: requests, nodeSelector, node affinity, tolerations,
topology-spread constraints, ownership, and scheduling status.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from types import MappingProxyType
from typing import Dict, List, Optional, Tuple

from karpenter_tpu.api import wellknown
from karpenter_tpu.api.requirements import Requirement, Requirements
from karpenter_tpu.api.resources import ResourceList, parse_resource_list
from karpenter_tpu.api.taints import Toleration

_uid_counter = itertools.count(1)

# Lazily-bound ops.encode.resource_vector (function-level import would pay
# import-machinery overhead per pod construction — ~9ms across a 50k storm;
# a module-level import would be circular, encode imports this module).
_resource_vector = None


def _dense_request_cache(parsed: Dict[str, float]):
    """(vector, vector bytes) — THE dense-vector cache format. Built here at
    construction and read by ops.encode.group_pods; one definition so the
    two sides cannot drift."""
    global _resource_vector
    if _resource_vector is None:
        from karpenter_tpu.ops.encode import resource_vector

        _resource_vector = resource_vector
    vec = _resource_vector(parsed)
    return vec, vec.tobytes()

PHASE_PENDING = "Pending"
PHASE_RUNNING = "Running"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str = DO_NOT_SCHEDULE
    # Simplified selector: pods match iff their labels contain all these pairs.
    match_labels: Dict[str, str] = field(default_factory=dict)

    def matches(self, labels: Dict[str, str]) -> bool:
        return all(labels.get(k) == v for k, v in self.match_labels.items())

    def group_key(self) -> Tuple:
        """Constraints with equal key are spread together
        (ref: scheduling/topology.go:57-75 hashes the constraint)."""
        return (
            self.max_skew,
            self.topology_key,
            self.when_unsatisfiable,
            tuple(sorted(self.match_labels.items())),
        )


@dataclass
class PreferredTerm:
    weight: int
    requirements: List[Requirement]


@dataclass
class PodSpec:
    name: str
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)

    # Effective resource requests (already folded across containers).
    requests: ResourceList = field(default_factory=dict)

    node_selector: Dict[str, str] = field(default_factory=dict)
    # Required node affinity: OR over terms, AND within a term.
    required_terms: List[List[Requirement]] = field(default_factory=list)
    # matchFields terms are modeled only so selection can reject them
    # (ref: selection/controller.go validate:108-159 — the provisioning path
    # doesn't support field selectors).
    match_fields_terms: List[dict] = field(default_factory=list)
    preferred_terms: List[PreferredTerm] = field(default_factory=list)
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread: List[TopologySpreadConstraint] = field(default_factory=list)
    # Inter-pod (anti-)affinity is unsupported by the provisioning path
    # (ref: selection/controller.go:117-123 rejects it); modeled only so
    # selection can reject such pods.
    pod_affinity_terms: List[dict] = field(default_factory=list)
    pod_anti_affinity_terms: List[dict] = field(default_factory=list)

    # Ownership / lifecycle.
    owner_kind: Optional[str] = None  # "DaemonSet", "Node", "ReplicaSet", ...
    priority_class_name: str = ""
    phase: str = PHASE_PENDING
    node_name: Optional[str] = None
    unschedulable: bool = False  # PodScheduled=False reason=Unschedulable
    deletion_timestamp: Optional[float] = None
    # metadata.creationTimestamp (epoch seconds): stamped by the cluster
    # store on first apply when absent, preserved across updates. The pod
    # lifecycle tracker (utils/obs.py) re-anchors its pending clock here
    # after a controller restart, so restart-spanning latency is charged.
    created_at: Optional[float] = None

    def __post_init__(self):
        if not self.uid:
            self.uid = f"pod-uid-{next(_uid_counter)}"
        # Always copy: never alias (and mutate) a caller-supplied dict.
        parsed = parse_resource_list(self.requests)
        # Every pod consumes one pod slot.
        parsed.setdefault(wellknown.RESOURCE_PODS, 1.0)
        # Read-only: the dense-vector cache below depends on requests never
        # changing after parsing, so that invariant is ENFORCED, not assumed
        # (mutating a proxy raises TypeError). Build changed requests into a
        # new PodSpec instead.
        self.requests = MappingProxyType(parsed)
        # Dense [R] request vector, computed HERE — construction is where
        # requests were just parsed, so the (memoized) dict->vector walk
        # happens once per pod at admission time, spread across the watch
        # stream, instead of 50k times inside the solve path's encode
        # (measured: ~35ms of a 50k-pod cold encode was exactly this walk).
        # ops.encode.group_pods reads the cache; requests immutability above
        # keeps it sound.
        self.dense_vector = _dense_request_cache(parsed)

    # --- predicates (ref: pkg/utils/pod/scheduling.go) ----------------------

    def is_scheduled(self) -> bool:
        return self.node_name is not None

    def is_terminal(self) -> bool:
        return self.phase in (PHASE_SUCCEEDED, PHASE_FAILED)

    def is_terminating(self) -> bool:
        return self.deletion_timestamp is not None

    def is_owned_by_daemonset(self) -> bool:
        return self.owner_kind == "DaemonSet"

    def is_owned_by_node(self) -> bool:
        return self.owner_kind == "Node"

    def failed_to_schedule(self) -> bool:
        return self.unschedulable

    def survives_node_drain(self) -> bool:
        """Worth disrupting when its node drains: not already dying, not
        bound to the node by ownership (daemon/static pods die with the
        node, they don't migrate). THE drain-eligibility predicate — the
        terminator's eviction set and the interruption drain's displacement
        set both read it, so they cannot disagree about which pods remain."""
        return not (
            self.is_terminating()
            or self.is_terminal()
            or self.is_owned_by_node()
            or self.is_owned_by_daemonset()
        )

    def is_provisionable(self) -> bool:
        """Candidate for provisioning: unschedulable, unbound, not daemon/static
        (ref: selection/controller.go isProvisionable:104)."""
        return (
            self.failed_to_schedule()
            and not self.is_scheduled()
            and not self.is_owned_by_daemonset()
            and not self.is_owned_by_node()
            and not self.is_terminal()
            and not self.is_terminating()
        )

    # --- scheduling requirements (ref: requirements.go PodRequirements:58-76)

    def scheduling_requirements(self) -> Requirements:
        """nodeSelector + the heaviest preferred term + the first required term.

        The reference deliberately collapses affinity OR-terms to the first
        term and preferences to the single heaviest — relaxation on retry is
        handled separately (selection/preferences.go).
        """
        requirements: List[Requirement] = [
            Requirement.in_(key, [value])
            for key, value in sorted(self.node_selector.items())
        ]
        if self.preferred_terms:
            heaviest = max(self.preferred_terms, key=lambda term: term.weight)
            requirements.extend(heaviest.requirements)
        if self.required_terms:
            requirements.extend(self.required_terms[0])
        return Requirements(requirements)

    def total_requests(self) -> ResourceList:
        return dict(self.requests)
