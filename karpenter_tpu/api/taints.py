"""Taints and tolerations.

Ref: pkg/apis/provisioning/v1alpha5/taints.go — provisioner taints must be
tolerated by every pod scheduled to its nodes, and pods with Equal-operator
tolerations imprint matching taints onto the nodes provisioned for them so
dedicated-node workflows work without pre-declaring taints.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

EFFECT_NO_SCHEDULE = "NoSchedule"
EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
EFFECT_NO_EXECUTE = "NoExecute"

OP_EXISTS = "Exists"
OP_EQUAL = "Equal"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = EFFECT_NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    key: str = ""
    operator: str = OP_EQUAL
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:
            # Empty key with Exists tolerates everything.
            return self.operator == OP_EXISTS
        if self.key != taint.key:
            return False
        if self.operator == OP_EXISTS:
            return True
        return self.value == taint.value


def taints_tolerate_pod(taints: Sequence[Taint], tolerations: Sequence[Toleration]) -> bool:
    """True iff every NoSchedule/NoExecute taint is tolerated by some toleration
    (PreferNoSchedule is advisory and never blocks; matches kube semantics and
    ref: taints.go Tolerates)."""
    for taint in taints:
        if taint.effect == EFFECT_PREFER_NO_SCHEDULE:
            continue
        if not any(toleration.tolerates(taint) for toleration in tolerations):
            return False
    return True


def taints_for_pod(
    existing: Sequence[Taint], tolerations: Sequence[Toleration]
) -> List[Taint]:
    """Existing taints plus taints imprinted from the pod's Equal tolerations
    (ref: taints.go WithPod — only fully-specified Equal tolerations generate
    taints, and only if no taint with that key/effect already exists)."""
    out = list(existing)
    for toleration in tolerations:
        if toleration.operator != OP_EQUAL or not toleration.key or not toleration.effect:
            continue
        if any(t.key == toleration.key and t.effect == toleration.effect for t in out):
            continue
        out.append(
            Taint(key=toleration.key, value=toleration.value, effect=toleration.effect)
        )
    return out
