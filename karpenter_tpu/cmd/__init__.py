"""Entry-point binaries (ref: cmd/controller, cmd/webhook)."""
