"""Admission webhook entry point.

Ref: cmd/webhook/main.go:44-96 — the reference runs knative admission
webhooks for CRD defaulting, CRD validation, and logging-config validation.
Here the same three behaviors are exposed as an HTTP service:

  POST /default   — provisioner JSON in, defaulted provisioner JSON out
  POST /validate  — provisioner JSON in, 200 or 422 with reasons
  POST /config    — {"level": "..."} live log-level reload
                    (ref: the config-logging ConfigMap validation webhook)

Run: python -m karpenter_tpu.cmd.webhook --cluster-name my-cluster
"""

from __future__ import annotations

import http.server
import json
import sys
import threading

from karpenter_tpu.api import validation
from karpenter_tpu.api.serialization import provisioner_from_dict, provisioner_to_dict
from karpenter_tpu.cloudprovider import registry
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils import options as options_pkg


class WebhookHandler(http.server.BaseHTTPRequestHandler):
    def _read_json(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    def _respond(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802
        try:
            data = self._read_json()
        except (ValueError, json.JSONDecodeError) as error:
            self._respond(400, {"error": f"invalid JSON: {error}"})
            return
        if self.path == "/default":
            try:
                provisioner = provisioner_from_dict(data)
                validation.default_provisioner(provisioner)
                self._respond(200, provisioner_to_dict(provisioner))
            except Exception as error:  # noqa: BLE001
                self._respond(400, {"error": str(error)})
        elif self.path == "/validate":
            try:
                provisioner = provisioner_from_dict(data)
                validation.validate_provisioner(provisioner)
                self._respond(200, {"allowed": True})
            except validation.ValidationError as error:
                self._respond(422, {"allowed": False, "reason": str(error)})
            except Exception as error:  # noqa: BLE001
                self._respond(400, {"error": str(error)})
        elif self.path == "/config":
            level = data.get("level") if isinstance(data, dict) else None
            if not isinstance(level, str) or level.lower() not in (
                "debug",
                "info",
                "warning",
                "error",
            ):
                self._respond(422, {"allowed": False, "reason": f"bad level {level!r}"})
                return
            klog.set_level(level)
            self._respond(200, {"allowed": True})
        else:
            self._respond(404, {"error": "not found"})

    def log_message(self, *args):
        pass


def main(argv=None, port: int = 8443, block: bool = True, address: str = ""):
    # --port belongs to this binary, not the shared options envelope
    # (the chart passes it; options.parse would reject the unknown flag).
    if argv:
        argv = list(argv)
        for i, arg in enumerate(list(argv)):
            if arg.startswith("--port="):
                port = int(arg.split("=", 1)[1])
                argv.pop(i)
                break
            if arg == "--port" and i + 1 < len(argv):
                port = int(argv[i + 1])
                del argv[i : i + 2]
                break
    options = options_pkg.parse(argv)
    klog.setup(options.log_level)
    registry.new_cloud_provider(options.cloud_provider)  # installs hooks
    server = http.server.ThreadingHTTPServer((address, port), WebhookHandler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    klog.named("webhook").info("webhook serving on :%d", port)
    if block:
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            pass
        server.shutdown()
    return server


if __name__ == "__main__":
    main(sys.argv[1:])
