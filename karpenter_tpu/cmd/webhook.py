"""Admission webhook entry point.

Ref: cmd/webhook/main.go:44-96 — the reference runs knative admission
webhooks for CRD defaulting, CRD validation, and logging-config validation.
Here the same behaviors are served over the Kubernetes AdmissionReview v1
protocol (so a real apiserver can call them) with a plain-JSON fallback:

  POST /default   — AdmissionReview in → AdmissionReview out with a base64
                    JSONPatch applying CRD defaulting (a mutating webhook);
                    plain provisioner JSON in → defaulted JSON out.
  POST /validate  — AdmissionReview in → AdmissionReview out with
                    allowed=true/false + status message (validating webhook);
                    plain JSON in → 200 or 422 with reasons.
  POST /config    — {"level": "..."} live log-level reload
                    (ref: the config-logging ConfigMap validation webhook)

TLS (the apiserver only calls HTTPS webhook endpoints), either:
  * --tls-self-signed [--tls-dns-names a,b,c] — self-provision a serving
    cert at startup, rotate it in-process before expiry, and inject the
    caBundle into the webhook configurations through the apiserver
    (ref: cmd/webhook/main.go:44-62 — knative's certificate controller;
    the chart's default, no operator secret needed), or
  * --tls-cert-file/--tls-key-file — operator-supplied certs mounted from
    a secret (e.g. cert-manager; chart webhook.tlsSecretName).

Run: python -m karpenter_tpu.cmd.webhook --cluster-name my-cluster
"""

from __future__ import annotations

import base64
import http.server
import json
import socket
import ssl
import sys
import threading
from typing import List, Optional

from karpenter_tpu.api import validation
from karpenter_tpu.api.serialization import provisioner_from_dict, provisioner_to_dict
from karpenter_tpu.cloudprovider import registry
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils import options as options_pkg


def admission_response(uid: str, allowed: bool, message: str = "", patch=None):
    """Build an AdmissionReview v1 response envelope."""
    response = {"uid": uid, "allowed": allowed}
    if message:
        response["status"] = {"code": 200 if allowed else 422, "message": message}
    if patch:
        response["patchType"] = "JSONPatch"
        response["patch"] = base64.b64encode(json.dumps(patch).encode()).decode()
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": response,
    }


def defaulting_patch(obj: dict) -> Optional[List[dict]]:
    """JSONPatch ops applying CRD defaulting to the admitted object.

    Defaulting only touches spec, so the patch is a single op carrying the
    defaulted spec. RFC 6902 'add' REPLACES an existing member, so the op is
    valid whether or not the original request carried a spec at all. The
    diff is taken against the object's own normalized round-trip, so pure
    serialization churn (quantity parsing etc.) produces no patch."""
    provisioner = provisioner_from_dict(obj)
    base = provisioner_to_dict(provisioner)  # snapshot before mutation
    validation.default_provisioner(provisioner)
    defaulted = provisioner_to_dict(provisioner)
    if defaulted.get("spec") == base.get("spec"):
        return None
    return [{"op": "add", "path": "/spec", "value": defaulted["spec"]}]


class WebhookHandler(http.server.BaseHTTPRequestHandler):
    def _read_json(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    def _respond(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _handle_admission_review(self, data) -> None:
        """AdmissionReview v1 (the protocol a real apiserver speaks).
        Admission outcomes ride inside a 200 envelope; only a malformed
        envelope is an HTTP error."""
        request = data.get("request") or {}
        uid = request.get("uid", "")
        obj = request.get("object")
        if not isinstance(obj, dict):
            self._respond(400, {"error": "AdmissionReview without request.object"})
            return
        if self.path == "/default":
            try:
                self._respond(
                    200, admission_response(uid, True, patch=defaulting_patch(obj))
                )
            except Exception as error:  # noqa: BLE001
                self._respond(200, admission_response(uid, False, str(error)))
        elif self.path == "/validate":
            try:
                provisioner = provisioner_from_dict(obj)
                validation.validate_provisioner(provisioner)
                self._respond(200, admission_response(uid, True))
            except Exception as error:  # noqa: BLE001 — invalid spec or parse
                self._respond(200, admission_response(uid, False, str(error)))
        else:
            self._respond(404, {"error": "not found"})

    def do_POST(self):  # noqa: N802
        try:
            data = self._read_json()
        except (ValueError, json.JSONDecodeError) as error:
            self._respond(400, {"error": f"invalid JSON: {error}"})
            return
        if isinstance(data, dict) and data.get("kind") == "AdmissionReview":
            self._handle_admission_review(data)
            return
        if self.path == "/default":
            try:
                provisioner = provisioner_from_dict(data)
                validation.default_provisioner(provisioner)
                self._respond(200, provisioner_to_dict(provisioner))
            except Exception as error:  # noqa: BLE001
                self._respond(400, {"error": str(error)})
        elif self.path == "/validate":
            try:
                provisioner = provisioner_from_dict(data)
                validation.validate_provisioner(provisioner)
                self._respond(200, {"allowed": True})
            except validation.ValidationError as error:
                self._respond(422, {"allowed": False, "reason": str(error)})
            except Exception as error:  # noqa: BLE001
                self._respond(400, {"error": str(error)})
        elif self.path == "/config":
            level = data.get("level") if isinstance(data, dict) else None
            if not isinstance(level, str) or level.lower() not in (
                "debug",
                "info",
                "warning",
                "error",
            ):
                self._respond(422, {"allowed": False, "reason": f"bad level {level!r}"})
                return
            klog.set_level(level)
            self._respond(200, {"allowed": True})
        else:
            self._respond(404, {"error": "not found"})

    def log_message(self, *args):
        pass


class _TLSHTTPServer(http.server.ThreadingHTTPServer):
    """HTTPS server that performs the TLS handshake in the PER-CONNECTION
    thread, with a timeout. Wrapping the listening socket instead would run
    handshakes inside the single accept loop — one idle TCP connection (port
    scanner, TCP health check) would wedge every admission call behind it."""

    HANDSHAKE_TIMEOUT_SECONDS = 10.0

    def __init__(self, addr, handler, context: ssl.SSLContext):
        super().__init__(addr, handler)
        self._tls_context = context

    def get_request(self):
        sock, addr = super().get_request()
        # Defer the handshake: it runs in finish_request, on this
        # connection's own thread.
        return (
            self._tls_context.wrap_socket(
                sock, server_side=True, do_handshake_on_connect=False
            ),
            addr,
        )

    def finish_request(self, request, client_address):
        request.settimeout(self.HANDSHAKE_TIMEOUT_SECONDS)
        request.do_handshake()
        request.settimeout(None)
        super().finish_request(request, client_address)

    def handle_error(self, request, client_address):
        # Handshake failures (scanners, health checks, truncated conns) are
        # expected noise — one quiet line. Anything else escaping request
        # handling is a real admission-path bug and must be loud.
        error = sys.exc_info()[1]
        if isinstance(error, (ssl.SSLError, socket.timeout, TimeoutError,
                              ConnectionResetError, BrokenPipeError)):
            klog.named("webhook").debug(
                "connection error from %s: %s", client_address, error
            )
        else:
            klog.named("webhook").exception(
                "unhandled error serving %s", client_address
            )


def _extract_flag(argv: list, name: str) -> Optional[str]:
    """Pop --name=value / --name value / bare --name from argv. Returns the
    value, "" for a bare flag (Go-style boolean), None when absent — a
    following argument that is itself a flag is never consumed as a value."""
    for i, arg in enumerate(list(argv)):
        if arg.startswith(f"--{name}="):
            argv.pop(i)
            return arg.split("=", 1)[1]
        if arg == f"--{name}":
            if i + 1 < len(argv) and not argv[i + 1].startswith("--"):
                value = argv[i + 1]
                del argv[i : i + 2]
                return value
            argv.pop(i)
            return ""
    return None


def _cluster_kube_client(options):
    """A KubeClient for the configured apiserver backend, or None for the
    in-memory store (shared selection: HttpTransport.for_store)."""
    from karpenter_tpu.kubeapi import KubeClient
    from karpenter_tpu.kubeapi.client import HttpTransport

    transport = HttpTransport.for_store(options.cluster_store)
    return None if transport is None else KubeClient(transport)


def main(
    argv=None,
    port: int = 8443,
    block: bool = True,
    address: str = "",
    tls_cert_file: Optional[str] = None,
    tls_key_file: Optional[str] = None,
    tls_self_signed: bool = False,
    tls_dns_names: Optional[List[str]] = None,
):
    # These flags belong to this binary, not the shared options envelope
    # (the chart passes them; options.parse would reject unknown flags).
    if argv:
        argv = list(argv)
        port_arg = _extract_flag(argv, "port")
        if port_arg:
            port = int(port_arg)
        tls_cert_file = _extract_flag(argv, "tls-cert-file") or tls_cert_file
        tls_key_file = _extract_flag(argv, "tls-key-file") or tls_key_file
        self_signed_arg = _extract_flag(argv, "tls-self-signed")
        if self_signed_arg is not None:
            # Bare --tls-self-signed means true, Go-flag style.
            tls_self_signed = self_signed_arg.lower() in ("true", "1", "yes", "")
        dns_arg = _extract_flag(argv, "tls-dns-names")
        if dns_arg:
            tls_dns_names = [d.strip() for d in dns_arg.split(",") if d.strip()]
    options = options_pkg.parse(argv)
    klog.setup(options.log_level)
    registry.new_cloud_provider(options.cloud_provider)  # installs hooks
    scheme = "http"
    cert_manager = None
    if not (tls_cert_file and tls_key_file) and tls_self_signed:
        # No operator-supplied secret: self-provision the serving cert,
        # rotate it in-process before expiry, and inject the caBundle into
        # the webhook configurations — the knative reference's certificate
        # controller behavior (ref: cmd/webhook/main.go:44-62).
        from karpenter_tpu.utils.certs import CertManager, inject_ca_bundle

        names = tls_dns_names or [
            "karpenter-tpu-webhook",
            "karpenter-tpu-webhook.karpenter.svc",
            "karpenter-tpu-webhook.karpenter.svc.cluster.local",
        ]
        cert_manager = CertManager(common_name=names[0], dns_names=names)
        tls_cert_file, tls_key_file = cert_manager.ensure()
        client = _cluster_kube_client(options)
        if client is not None:
            def _inject(ca_b64: str, client=client):
                inject_ca_bundle(client, ca_b64)

            cert_manager.on_rotate = _inject
            try:
                _inject(cert_manager.ca_bundle_b64())
            except Exception:  # noqa: BLE001 — registration may come later
                klog.named("webhook").exception("initial caBundle injection failed")
    if tls_cert_file and tls_key_file:
        # The apiserver only calls HTTPS webhook endpoints. Certs are either
        # operator-mounted (chart webhook.tlsSecretName) or self-provisioned
        # above; self-provisioned contexts hot-reload on rotation.
        context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        context.load_cert_chain(tls_cert_file, tls_key_file)
        server = _TLSHTTPServer((address, port), WebhookHandler, context)
        scheme = "https"
        if cert_manager is not None:
            cert_manager.register_context(context)
            cert_manager.start_rotation_thread()
            server.cert_manager = cert_manager
    else:
        server = http.server.ThreadingHTTPServer((address, port), WebhookHandler)
    threading.Thread(
        target=server.serve_forever, name="webhook-serve", daemon=True
    ).start()
    klog.named("webhook").info("webhook serving %s on :%d", scheme, port)
    if block:
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            pass
        server.shutdown()
    return server


if __name__ == "__main__":
    main(sys.argv[1:])
