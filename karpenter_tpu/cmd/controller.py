"""Controller entry point.

Ref: cmd/controller/main.go:61-99 — parse options, build logging, acquire
leadership, construct the cloud provider (installing its API hooks), register
all controllers, serve metrics + health.

Run: python -m karpenter_tpu.cmd.controller --cluster-name my-cluster
"""

from __future__ import annotations

import os
import signal
import sys
import threading

from karpenter_tpu.cloudprovider import registry
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.runtime import LeaderElector, LeaderLock, Manager, serve_http
from karpenter_tpu.utils.gctune import tune_gc
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils import options as options_pkg


def build_cluster(options) -> Cluster:
    """Select the cluster-store backend (ref: cmd/controller/main.go:61-99 —
    the reference always reconciles a live apiserver; --cluster-store wires
    the same here, with the in-memory store for standalone/dev runs)."""
    from karpenter_tpu.kubeapi import ApiServerCluster, KubeClient, RetryPolicy
    from karpenter_tpu.kubeapi.client import HttpTransport

    transport = HttpTransport.for_store(options.cluster_store)
    if transport is None:
        return Cluster()
    transport.watch_idle_s = options.kube_watch_idle_timeout
    client = KubeClient(
        transport,
        qps=options.kube_client_qps,
        burst=options.kube_client_burst,
        retry=RetryPolicy(
            max_attempts=options.kube_retry_max_attempts,
            backoff_base_s=options.kube_retry_backoff_base,
            backoff_cap_s=options.kube_retry_backoff_cap,
        ),
    )
    return ApiServerCluster(client).start()


def main(argv=None, cluster: Cluster = None, block: bool = True) -> Manager:
    tune_gc()  # long-running service: GOGC-style collector headroom
    from karpenter_tpu.ops.pack_kernel import suppress_donation_advisory

    suppress_donation_advisory()  # CPU-fallback rigs warn per compile
    options = options_pkg.parse(argv)
    log = klog.setup(options.log_level)
    log.info(
        "starting karpenter-tpu controller for cluster %s (store=%s)",
        options.cluster_name,
        options.cluster_store,
    )

    cluster = cluster if cluster is not None else build_cluster(options)
    cloud = registry.new_cloud_provider(options.cloud_provider)
    # Manager is constructed (but not started) before the campaign so the
    # lease-loss callback has something concrete to stop — no window where a
    # loss arrives with nothing wired.
    manager = Manager(cluster, cloud, options)
    stop = threading.Event()

    def on_lost_lease():
        # Reference behavior: a deposed leader must stop reconciling and get
        # replaced (cmd/controller/main.go exits on lost lease). Stopping the
        # manager flips /healthz to 503 so the liveness probe restarts the
        # pod; in block mode the process also exits.
        log.error("leadership lost; stopping controller")
        manager.stop()
        stop.set()

    identity = f"{os.uname().nodename}-{os.getpid()}"
    # Two layers of mutual exclusion: the host-level file lock guards
    # multiple processes on one machine; the store-level lease guards
    # replicas ONLY when they share a cluster store. With the default
    # in-memory store each replica holds its own private lease, so there is
    # no cross-replica exclusion — the chart pins replicas to 1 for exactly
    # this reason (values.yaml). An apiserver-backed store makes the lease a
    # real coordination.k8s.io Lease and lifts that restriction.
    file_lock = LeaderLock()
    elector = LeaderElector(cluster, identity, on_lost=on_lost_lease)
    # Probe + metrics servers come up BEFORE the campaign: a campaigning
    # standby must answer /healthz 200 and /readyz 503 "standby", or the
    # liveness probe kills every replica that isn't currently leader and
    # there is never a warm standby to fail over to.
    serve_http(manager, options.metrics_port)
    # Separate probe port, matching the reference's split (manager.go:52-57)
    # and the chart's liveness/readiness wiring.
    serve_http(manager, options.health_probe_port)
    if options.leader_election:
        log.info("campaigning for leadership as %s", identity)
        # Warm standby while waiting: watch pump + informer cache +
        # DeviceClusterState sync are already live (cluster built above);
        # this pre-pays the solver compile debt so takeover has bounded
        # time-to-first-launch.
        manager.start_standby()
        file_lock.acquire(blocking=True)
        campaign_began = cluster.clock.now()
        elector.acquire(blocking=True)
        lease = cluster.get_lease(LeaderElector.LEASE_NAME)
        log.info(
            "leadership acquired after %.1fs; holder %s generation %s",
            cluster.clock.now() - campaign_began,
            lease and lease[0],
            elector.generation,
        )

    manager.start()
    log.info(
        "controller ready: metrics on :%d, health on :%d, solver=%s, cloud=%s",
        options.metrics_port,
        options.health_probe_port,
        options.solver,
        options.cloud_provider,
    )

    if block:
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())

        def on_sighup(*_):
            # Live reload of the RELOADABLE subset (log level, SLO targets):
            # re-parse the original argv, which re-reads env fallbacks too.
            try:
                fresh = options_pkg.parse(argv)
            except Exception:  # noqa: BLE001 — a bad env edit must not kill us
                log.exception("SIGHUP reload failed; keeping current options")
                return
            manager.reload_options(options_pkg.apply_reload(options, fresh))

        signal.signal(signal.SIGHUP, on_sighup)
        stop.wait()
        manager.stop()
        elector.release()
        file_lock.release()
    return manager


if __name__ == "__main__":
    main(sys.argv[1:])
