"""Controller entry point.

Ref: cmd/controller/main.go:61-99 — parse options, build logging, acquire
leadership, construct the cloud provider (installing its API hooks), register
all controllers, serve metrics + health.

Run: python -m karpenter_tpu.cmd.controller --cluster-name my-cluster
"""

from __future__ import annotations

import signal
import sys
import threading

from karpenter_tpu.cloudprovider import registry
from karpenter_tpu.controllers.cluster import Cluster
from karpenter_tpu.runtime import LeaderLock, Manager, serve_http
from karpenter_tpu.utils import logging as klog
from karpenter_tpu.utils import options as options_pkg


def main(argv=None, cluster: Cluster = None, block: bool = True) -> Manager:
    options = options_pkg.parse(argv)
    log = klog.setup(options.log_level)
    log.info("starting karpenter-tpu controller for cluster %s", options.cluster_name)

    lock = LeaderLock()
    if options.leader_election:
        log.info("acquiring leader lock")
        lock.acquire(blocking=True)

    cloud = registry.new_cloud_provider(options.cloud_provider)
    cluster = cluster if cluster is not None else Cluster()
    manager = Manager(cluster, cloud, options)
    manager.start()
    serve_http(manager, options.metrics_port)
    # Separate probe port, matching the reference's split (manager.go:52-57)
    # and the chart's liveness/readiness wiring.
    serve_http(manager, options.health_probe_port)
    log.info(
        "controller ready: metrics on :%d, health on :%d, solver=%s, cloud=%s",
        options.metrics_port,
        options.health_probe_port,
        options.solver,
        options.cloud_provider,
    )

    if block:
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        stop.wait()
        manager.stop()
        lock.release()
    return manager


if __name__ == "__main__":
    main(sys.argv[1:])
