"""karpenter_tpu — a TPU-native cluster node-provisioning framework.

A ground-up rebuild of the capabilities of Karpenter (reference:
/root/reference, aws/karpenter v0.5.x era): watch unschedulable pods, group
them by scheduling constraints, bin-pack them onto candidate instance types
and zones, launch + bind capacity, and manage node lifecycle — with the
provisioning solver reformulated as batched tensor math on TPU
(JAX / pjit / lax.scan) instead of the reference's sequential greedy
First-Fit-Decreasing loop (reference: pkg/controllers/provisioning/binpacking).

Layout:
  api/            typed spec model: Provisioner, Constraints, Requirements,
                  Taints, Limits + validation/defaulting (ref pkg/apis/provisioning/v1alpha5)
  ops/            tensor kernels: spec encoding, FFD pack kernel, batched
                  scoring + LP relaxation, topology-spread masks
  models/         solver models: greedy fallback, TPU batched solver,
                  differentiable assignment model (the flagship)
  parallel/       device mesh + sharding for multi-chip solves
  controllers/    control plane: selection, provisioning batcher, scheduler,
                  termination, node lifecycle, counter, metrics
  cloudprovider/  CloudProvider/InstanceType/Offering interfaces, fake provider
  utils/          resource arithmetic, clock, rate-limited queues
"""

__version__ = "0.1.0"
