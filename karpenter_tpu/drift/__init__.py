"""Drift: canonical Provisioner spec hashing + drift kinds.

Production Karpenter stamps a hash of the provisioner spec onto every node it
creates (`karpenter.sh/provisioner-hash`) and treats a mismatch between the
stamped hash and the live spec as *drift* — the node was built from an older
generation of the spec and should be replaced in a budgeted rolling wave
(controllers/drift.py). This module owns the hash canon.

Design rules (docs/design/drift.md):

- The hash covers the STORED (user-declared) constraint envelope only:
  labels, taints, requirements, and the vendor provider config. It is what
  the operator edits, and what a node's shape was derived from.
- Lifecycle knobs — TTLs, limits, weight — are EXCLUDED: flipping
  `ttlSecondsUntilExpired` or a resource limit must not roll the fleet.
- The effective (fleet-refreshed) spec the provisioning worker solves
  against is NEVER hashed: catalog refreshes and ICE blackouts mutate it
  continuously, and hashing it would turn every market wobble into fleet
  drift. (`provisioning.spec_hash` — a Python `hash()` over the effective
  spec — exists for worker hot-swap and stays separate on purpose.)
- The hash is order-insensitive and process-stable: canonical JSON
  (sorted keys, sorted collections) under sha256, so two specs that differ
  only in declaration order — or a restarted controller re-hashing the same
  spec — agree bit-for-bit. Python's `hash()` is salted per process and
  must never leak into a stamped annotation.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict

from karpenter_tpu.api.provisioner import Provisioner, ProvisionerSpec

# Drift kinds — the value stamped into DRIFT_ACTION_ANNOTATION. "spec" =
# stamped hash no longer matches the live spec; "provider" = the cloud says
# the instance's template/AMI/offering moved; "expired" = the node outlived
# ttlSecondsUntilExpired (expiration rides the same rolling wave).
DRIFT_KIND_SPEC = "spec"
DRIFT_KIND_PROVIDER = "provider"
DRIFT_KIND_EXPIRED = "expired"
DRIFT_KINDS = (DRIFT_KIND_SPEC, DRIFT_KIND_PROVIDER, DRIFT_KIND_EXPIRED)

# Short, annotation-friendly prefix of the sha256. 16 hex chars = 64 bits;
# collisions across the handful of spec generations a fleet ever sees are
# not a real risk, and operators read these by eye in `kubectl describe`.
HASH_LENGTH = 16


def _canonical_envelope(spec: ProvisionerSpec) -> Dict[str, Any]:
    """The hashed payload, as plain JSON-able data with every collection in
    canonical order. Key names are part of the canon — renaming one rolls
    every fleet on upgrade, so don't."""
    constraints = spec.constraints
    return {
        "labels": sorted(constraints.labels.items()),
        "taints": sorted(
            (t.key, t.value, t.effect) for t in constraints.taints
        ),
        # canonical_key() is already sorted + complement-aware: two
        # Requirements built in different order (or with duplicate merges)
        # agree here.
        "requirements": constraints.requirements.canonical_key(),
        "provider": constraints.provider,
    }


def spec_hash(provisioner_or_spec) -> str:
    """Canonical, order-insensitive, cross-process-stable hash of the
    Provisioner constraint envelope. Accepts a Provisioner or a
    ProvisionerSpec."""
    spec = (
        provisioner_or_spec.spec
        if isinstance(provisioner_or_spec, Provisioner)
        else provisioner_or_spec
    )
    payload = json.dumps(
        _canonical_envelope(spec),
        sort_keys=True,
        separators=(",", ":"),
        default=str,  # backstop for exotic provider values; str() is stable
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:HASH_LENGTH]
